"""Array creation routines (reference ``heat/core/factories.py``).

Key TPU-native difference: with ``split=`` given, arrays are created
**directly sharded on device** via a jitted creator with ``out_shardings`` —
nothing global is materialized on the host first. The reference instead
materializes the *full* global tensor on every rank and then slices
(``factories.py:318-378``), which SURVEY.md flags as a hot issue to avoid.
"""

from __future__ import annotations

from typing import Iterable, Optional, Sequence, Tuple, Union

import numpy as np

import jax
import jax.numpy as jnp

from . import devices, memory, types
from .communication import sanitize_comm
from .dndarray import DNDarray
from .stride_tricks import sanitize_axis, sanitize_shape

__all__ = [
    "arange",
    "array",
    "asarray",
    "empty",
    "empty_like",
    "eye",
    "from_partitioned",
    "full",
    "full_like",
    "linspace",
    "logspace",
    "meshgrid",
    "ones",
    "ones_like",
    "tri",
    "tril_indices",
    "triu_indices",
    "vander",
    "zeros",
    "zeros_like",
]

# cache of jitted sharded creators keyed by (tag, gshape, dtype, split, mesh-id)
_CREATE_CACHE: dict = {}


def _sharded_create(tag, make_logical, gshape, jdtype, split, comm):
    """jit-compile ``make_logical`` (a closure producing the logical array)
    padded to the canonical physical shape, created directly with the target
    sharding so no unsharded intermediate exists."""
    gshape = tuple(int(s) for s in gshape)
    if split is not None and (not gshape or gshape[split] == 0 or 0 in gshape):
        split = None  # zero-size axes are placed replicated
    key = (tag, gshape, str(jdtype), split, comm.cache_key)
    fn = _CREATE_CACHE.get(key)
    if fn is None:
        sharding = comm.sharding(len(gshape), split)

        def _go():
            arr = make_logical()
            if split is not None and len(gshape):
                pad = comm.padded_size(gshape[split]) - gshape[split]
                if pad:
                    cfg = [(0, pad if i == split else 0) for i in range(len(gshape))]
                    arr = jnp.pad(arr, cfg)
            return arr

        fn = jax.jit(_go, out_shardings=sharding)
        _CREATE_CACHE[key] = fn
    return fn()


def _contains_numpy64_leaf(obj) -> bool:
    """True when a (possibly nested) python sequence holds 64-bit-float or
    -complex NumPy data — an f64/c128 ndarray, or a np.float64/np.complex128
    scalar hiding behind its python-number subclass. Such sequences keep
    NumPy's inferred dtype (torch.tensor([np.float64(x)]) is float64).
    Everything else — pure python, or 32-bit NumPy leaves mixed with weak
    python numbers (torch.tensor([np.float32(x), 2.5]) is float32) — takes
    the reference's float32/complex64 ladder."""
    if isinstance(obj, np.ndarray):
        return obj.dtype in (np.float64, np.complex128)
    if isinstance(obj, np.generic):
        return isinstance(obj, (np.float64, np.complex128))
    if isinstance(obj, (list, tuple)):
        return any(_contains_numpy64_leaf(e) for e in obj)
    return False


def array(
    obj,
    dtype=None,
    copy: bool = True,
    ndmin: int = 0,
    order: str = "C",
    split: Optional[int] = None,
    is_split: Optional[int] = None,
    device=None,
    comm=None,
) -> DNDarray:
    """Create a DNDarray (reference ``factories.py:150-431``).

    ``split=k``: shard the global object along axis ``k``. ``is_split=k``:
    adopt pre-distributed chunks — under a single controller the provided
    object *is* the full process-local data, so this is equivalent to
    ``split=k`` (the reference's neighbor shape negotiation,
    ``factories.py:385-430``, has no multi-process analogue here).
    """
    if split is not None and is_split is not None:
        raise ValueError("split and is_split are mutually exclusive")
    if is_split is not None:
        split = is_split

    device = devices.sanitize_device(device)
    comm = sanitize_comm(comm)

    if isinstance(obj, DNDarray):
        if dtype is not None and types.canonical_heat_type(dtype) is not obj.dtype:
            obj = obj.astype(dtype)
        elif copy:
            obj = DNDarray(obj.larray, obj.gshape, obj.dtype, obj.split, obj.device, obj.comm)
        if split is not None:
            split = sanitize_axis(obj.shape, split)
        if split != obj.split:
            obj = obj.resplit(split)
        return obj

    if dtype is not None:
        dtype = types.canonical_heat_type(dtype)
        arr = jnp.asarray(obj, dtype=dtype.jax_type())
    else:
        if (isinstance(obj, (list, tuple, int, float, bool, complex))
                and not isinstance(obj, np.generic)
                and not _contains_numpy64_leaf(obj)):
            # np.float64/np.complex128 scalars subclass python float/complex
            # but must keep their dtype like any other NumPy input — bare
            # (np.generic guard) or nested in a sequence (leaf scan; torch
            # infers float64 for [np.float64(x)] and for lists of f64 rows).
            # reference-parity inference for python data (the torch.tensor
            # ladder, factories.py:318-331): floats -> float32, complex ->
            # complex64, ints stay 64-bit. Also the TPU-right default —
            # float64 would double HBM traffic and fall off the MXU.
            arr = np.asarray(obj)
            if arr.dtype == np.float64:
                arr = arr.astype(np.float32)
            elif arr.dtype == np.complex128:
                arr = arr.astype(np.complex64)
            arr = jnp.asarray(arr)
        else:
            # array-like inputs (NumPy/jax/DNDarray buffers) keep their dtype
            arr = jnp.asarray(obj)
        dtype = types.canonical_heat_type(arr.dtype)
    # on a single CPU device jnp.asarray may zero-copy-alias the caller's
    # NumPy buffer (alignment-dependent); honor copy=True with a real copy,
    # but only when the buffer is actually shared — big device arrays that
    # jax already copied shouldn't pay a second pass
    if copy and isinstance(obj, np.ndarray) and arr.size:
        try:
            aliased = (
                arr.addressable_data(0).unsafe_buffer_pointer() == obj.ctypes.data
            )
        except Exception:
            aliased = True
        if aliased:
            arr = jnp.array(arr, copy=True)

    while arr.ndim < ndmin:
        arr = arr[jnp.newaxis]

    if split is not None:
        split = sanitize_axis(arr.shape, split)
    return DNDarray.from_logical(arr, split, device, comm, dtype=dtype)


def asarray(obj, dtype=None, copy=None, order="C", is_split=None, device=None) -> DNDarray:
    """No-copy-when-possible array creation (reference ``factories.py:434``)."""
    memory.sanitize_memory_order(order)
    return array(obj, dtype=dtype, copy=bool(copy), is_split=is_split, device=device)


def arange(*args, dtype=None, split=None, device=None, comm=None) -> DNDarray:
    """Evenly spaced values in a range (reference ``factories.py:40-147``)."""
    num_args = len(args)
    if num_args == 1:
        start, stop, step = 0, args[0], 1
    elif num_args == 2:
        start, stop, step = args[0], args[1], 1
    elif num_args == 3:
        start, stop, step = args
    else:
        raise TypeError(f"arange takes 1 to 3 positional arguments, got {num_args}")

    device = devices.sanitize_device(device)
    comm = sanitize_comm(comm)

    if dtype is None:
        if all(isinstance(a, (int, np.integer)) for a in (start, stop, step)):
            jdtype = jnp.dtype("int64") if jax.config.jax_enable_x64 else jnp.dtype("int32")
        else:
            jdtype = jnp.dtype("float32")
    else:
        jdtype = types.canonical_heat_type(dtype).jax_type()

    n = max(0, int(np.ceil((stop - start) / step)))
    gshape = (n,)
    if split is not None:
        split = sanitize_axis(gshape, split)
    if jnp.issubdtype(jdtype, jnp.integer):
        make = lambda: jnp.arange(int(start), int(start) + n * int(step), int(step), dtype=jdtype)
    else:
        make = lambda: jnp.arange(n, dtype=jdtype) * jnp.asarray(step, jdtype) + jnp.asarray(
            start, jdtype
        )
    parray = _sharded_create(
        ("arange", float(start), float(step)), make, gshape, jdtype, split, comm
    )
    out = DNDarray(parray, gshape, types.canonical_heat_type(jdtype), split, device, comm)
    out._pad_zero = True  # _sharded_create's jnp.pad zero-fills the padding
    return out


def __factory(shape, dtype, split, device, comm, fill_tag, make) -> DNDarray:
    """Shared creation path (reference ``__factory``, ``factories.py:665``)."""
    shape = sanitize_shape(shape)
    device = devices.sanitize_device(device)
    comm = sanitize_comm(comm)
    dtype = types.canonical_heat_type(dtype)
    jdtype = dtype.jax_type()
    if split is not None:
        split = sanitize_axis(shape, split)
        if len(shape) == 0:
            split = None
    parray = _sharded_create(fill_tag, lambda: make(shape, jdtype), shape, jdtype, split, comm)
    out = DNDarray(parray, shape, dtype, split, device, comm)
    out._pad_zero = True  # _sharded_create's jnp.pad zero-fills the padding
    return out


def empty(shape, dtype=types.float32, split=None, device=None, comm=None, order="C") -> DNDarray:
    """Uninitialized (here: zero) array (reference ``factories.py:488``)."""
    memory.sanitize_memory_order(order)
    return __factory(shape, dtype, split, device, comm, "empty", lambda s, d: jnp.zeros(s, d))


def zeros(shape, dtype=types.float32, split=None, device=None, comm=None, order="C") -> DNDarray:
    """Zeros (reference ``factories.py:1246``)."""
    memory.sanitize_memory_order(order)
    return __factory(shape, dtype, split, device, comm, "zeros", lambda s, d: jnp.zeros(s, d))


def ones(shape, dtype=types.float32, split=None, device=None, comm=None, order="C") -> DNDarray:
    """Ones (reference ``factories.py:1118``)."""
    memory.sanitize_memory_order(order)
    return __factory(shape, dtype, split, device, comm, "ones", lambda s, d: jnp.ones(s, d))


def full(shape, fill_value, dtype=types.float32, split=None, device=None, comm=None, order="C") -> DNDarray:
    """Constant fill (reference ``factories.py:786``).

    The reference defaults ``dtype`` to float32 regardless of the fill's
    type (``factories.py:792``; ``ht.full((2,), 4)`` is float32, pinned by
    its ``test_full``) — pass ``dtype=None`` to infer from ``fill_value``.
    A complex fill upgrades a non-complex dtype to complex64 (reference
    ``factories.py:841-842`` — a float dtype would silently drop the
    imaginary part); unlike the reference's blanket override, an explicitly
    requested complex dtype (e.g. complex128) is honored.
    """
    memory.sanitize_memory_order(order)
    # np.complexfloating too: np.complex64 does NOT subclass python complex,
    # and float()-ing it would raise rather than warn
    if isinstance(fill_value, (complex, np.complexfloating)):
        if dtype is None and isinstance(fill_value, np.generic):
            dtype = types.heat_type_of(fill_value)  # np.complex64/128 kept
        elif dtype is None or not types.heat_type_is_complexfloating(
                types.canonical_heat_type(dtype)):
            dtype = types.complex64
    elif dtype is None:
        dtype = types.heat_type_of(fill_value)
    fv = (float(fill_value)
          if not isinstance(fill_value, (complex, np.complexfloating))
          else complex(fill_value))
    return __factory(
        shape, dtype, split, device, comm, ("full", fv), lambda s, d: jnp.full(s, fill_value, d)
    )


def __factory_like(a, dtype, split, device, comm, factory, **kwargs) -> DNDarray:
    """Shared *_like path (reference ``__factory_like``, ``factories.py:719``)."""
    shape = a.shape if hasattr(a, "shape") else np.asarray(a).shape
    if dtype is None:
        dtype = a.dtype if isinstance(a, DNDarray) else types.canonical_heat_type(np.asarray(a).dtype)
    if split is None:
        split = a.split if isinstance(a, DNDarray) else None
    if device is None and isinstance(a, DNDarray):
        device = a.device
    if comm is None and isinstance(a, DNDarray):
        comm = a.comm
    return factory(shape, dtype=dtype, split=split, device=device, comm=comm, **kwargs)


def empty_like(a, dtype=None, split=None, device=None, comm=None, order="C") -> DNDarray:
    memory.sanitize_memory_order(order)
    return __factory_like(a, dtype, split, device, comm, empty)


def zeros_like(a, dtype=None, split=None, device=None, comm=None, order="C") -> DNDarray:
    memory.sanitize_memory_order(order)
    return __factory_like(a, dtype, split, device, comm, zeros)


def ones_like(a, dtype=None, split=None, device=None, comm=None, order="C") -> DNDarray:
    memory.sanitize_memory_order(order)
    return __factory_like(a, dtype, split, device, comm, ones)


def full_like(a, fill_value, dtype=types.float32, split=None, device=None, comm=None, order="C") -> DNDarray:
    """Reference parity: like ``full``, dtype defaults to float32 — NOT to
    ``a.dtype`` (``factories.py:849``); ``dtype=None`` infers from the fill."""
    memory.sanitize_memory_order(order)
    if dtype is None:
        dtype = types.heat_type_of(fill_value)
    return __factory_like(a, dtype, split, device, comm, full, fill_value=fill_value)


def eye(shape, dtype=types.float32, split=None, device=None, comm=None, order="C") -> DNDarray:
    """Identity-like matrix (reference ``factories.py:586``)."""
    memory.sanitize_memory_order(order)
    if isinstance(shape, (int, np.integer)):
        n, m = int(shape), int(shape)
    else:
        shape = tuple(shape)
        if len(shape) == 1:
            n, m = int(shape[0]), int(shape[0])
        else:
            n, m = int(shape[0]), int(shape[1])
    return __factory(
        (n, m), dtype, split, device, comm, "eye", lambda s, d: jnp.eye(s[0], s[1], dtype=d)
    )


def linspace(
    start,
    stop,
    num: int = 50,
    endpoint: bool = True,
    retstep: bool = False,
    dtype=None,
    split=None,
    device=None,
    comm=None,
):
    """Evenly spaced samples over an interval (reference ``factories.py:896``)."""
    num = int(num)
    if num <= 0:
        raise ValueError(f"number of samples 'num' must be positive, got {num}")
    step = (stop - start) / max(1, (num - 1 if endpoint else num))
    if dtype is None:
        dtype = types.float32
    dtype = types.canonical_heat_type(dtype)
    jdtype = dtype.jax_type()
    gshape = (num,)
    if split is not None:
        split = sanitize_axis(gshape, split)
    comm_ = sanitize_comm(comm)
    device = devices.sanitize_device(device)
    parray = _sharded_create(
        ("linspace", float(start), float(stop), bool(endpoint)),
        lambda: jnp.linspace(start, stop, num, endpoint=endpoint, dtype=jdtype),
        gshape,
        jdtype,
        split,
        comm_,
    )
    result = DNDarray(parray, gshape, dtype, split, device, comm_)
    result._pad_zero = True  # _sharded_create's jnp.pad zero-fills the padding
    if retstep:
        return result, step
    return result


def logspace(
    start,
    stop,
    num: int = 50,
    endpoint: bool = True,
    base: float = 10.0,
    dtype=None,
    split=None,
    device=None,
    comm=None,
) -> DNDarray:
    """Log-spaced samples (reference ``factories.py:982``)."""
    from . import exponential

    y = linspace(start, stop, num=num, endpoint=endpoint, split=split, device=device, comm=comm)
    from . import arithmetics

    result = arithmetics.pow(float(base), y)
    if dtype is not None:
        return result.astype(types.canonical_heat_type(dtype))
    return result


def meshgrid(*arrays, indexing: str = "xy"):
    """Coordinate matrices from coordinate vectors (reference ``factories.py:1045``).

    The reference splits the second output dimension when any input is split;
    here outputs inherit ``split=None`` unless an input is split, in which
    case outputs are split along that input's broadcast dimension.
    """
    if indexing not in ("xy", "ij"):
        raise ValueError("indexing must be 'xy' or 'ij'")
    if not arrays:
        return []
    splits = [a.split if isinstance(a, DNDarray) else None for a in arrays]
    # determine output split: first split input determines it (numpy's xy
    # swap of the first two grid dims only exists for >= 2 inputs)
    out_split = None
    for i, s in enumerate(splits):
        if s is not None:
            dim = i
            if indexing == "xy" and i < 2 and len(arrays) >= 2:
                dim = 1 - i
            out_split = dim
            break
    device = next((a.device for a in arrays if isinstance(a, DNDarray)), None)
    comm = next((a.comm for a in arrays if isinstance(a, DNDarray)), None)
    comm_s = sanitize_comm(comm)
    nd = len(arrays)
    if out_split is not None and comm_s.size > 1:
        # gather-free construction: each output is its 1-D vector reshaped
        # to a unit-broadcast view and expanded shard-locally into the
        # sharded target (the outputs are O(prod of all axes) big — the old
        # path materialized every one of them logically)
        def vec(a):
            if isinstance(a, DNDarray):
                return a if a.ndim == 1 else a.reshape((a.size,))
            return array(jnp.asarray(a).reshape(-1), comm=comm_s,
                         device=device)

        vecs = [vec(a) for a in arrays]
        grid_of = list(range(nd))
        if indexing == "xy" and nd >= 2:
            grid_of[0], grid_of[1] = 1, 0
        shape = [0] * nd
        for i, v in enumerate(vecs):
            shape[grid_of[i]] = v.shape[0]
        shape = tuple(shape)
        if all(shape):  # zero-size axes: XLA replicates empty outputs and
            # rejects the sharding constraint — the logical path handles them
            phys_shape = tuple(
                comm_s.padded_size(shape[d]) if d == out_split else shape[d]
                for d in range(nd))
            fn = jax.jit(jnp.broadcast_to, static_argnums=(1,),
                         out_shardings=comm_s.sharding(nd, out_split))
            outs = []
            for i, v in enumerate(vecs):
                pos = grid_of[i]
                if pos == out_split and v.split == 0:
                    base = v.larray  # keeps its shards; padding replicates
                else:
                    if v.split is not None:
                        v = v.resplit(None)
                    base = v._logical()  # a coordinate vector: O(axis) tiny
                reshaped = base.reshape(
                    tuple(phys_shape[d] if d == pos else 1
                          for d in range(nd)))
                outs.append(DNDarray(
                    fn(reshaped, phys_shape), shape, v.dtype, out_split,
                    v.device, comm_s))
            return outs
    logicals = [a._logical() if isinstance(a, DNDarray) else jnp.asarray(a) for a in arrays]
    outs = jnp.meshgrid(*logicals, indexing=indexing)
    return [DNDarray.from_logical(o, out_split, device, comm) for o in outs]


def from_partitioned(x, comm=None) -> DNDarray:
    """Adopt an existing (possibly sharded) jax.Array as a DNDarray."""
    comm = sanitize_comm(comm)
    arr = jnp.asarray(x)
    # detect a sharded dimension
    split = None
    try:
        spec = arr.sharding.spec  # type: ignore[attr-defined]
        for i, s in enumerate(spec):
            if s is not None:
                split = i
                break
    except AttributeError:
        pass
    return DNDarray.from_logical(arr, split, devices.get_device(), comm)


def tri(N: int, M=None, k: int = 0, dtype=types.float32, split=None,
        device=None, comm=None) -> DNDarray:
    """Lower-triangular ones matrix (``numpy.tri``)."""
    M = N if M is None else M
    return array(np.tri(int(N), int(M), int(k)), dtype=dtype, split=split,
                 device=device, comm=comm)


def tril_indices(n: int, k: int = 0, m=None, split=None, comm=None):
    """Row/col indices of the lower triangle (``numpy.tril_indices``)."""
    rows, cols = np.tril_indices(int(n), int(k), None if m is None else int(m))
    return (array(rows, dtype=types.int64, split=split, comm=comm),
            array(cols, dtype=types.int64, split=split, comm=comm))


def triu_indices(n: int, k: int = 0, m=None, split=None, comm=None):
    """Row/col indices of the upper triangle (``numpy.triu_indices``)."""
    rows, cols = np.triu_indices(int(n), int(k), None if m is None else int(m))
    return (array(rows, dtype=types.int64, split=split, comm=comm),
            array(cols, dtype=types.int64, split=split, comm=comm))


def vander(x: DNDarray, N=None, increasing: bool = False) -> DNDarray:
    """Vandermonde matrix (``numpy.vander``): built as distributed
    broadcast powers — a split input yields a row-split result."""
    from . import arithmetics

    if not isinstance(x, DNDarray):
        x = array(np.asarray(x))
    if x.ndim != 1:
        raise ValueError("vander expects a 1-D array")
    N = x.shape[0] if N is None else int(N)
    exps = np.arange(N) if increasing else np.arange(N - 1, -1, -1)
    col = x.reshape((x.shape[0], 1))
    return arithmetics.pow(col, array(exps[None, :], dtype=x.dtype,
                                      comm=x.comm))
