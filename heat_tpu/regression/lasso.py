"""Lasso regression (reference ``heat/regression/lasso.py``).

Coordinate descent with soft thresholding (reference ``lasso.py:90-176``):
the per-feature loop runs on the controller, each sweep's matvecs are
distributed GEMMs with GSPMD psum. Feature count is the loop bound exactly
as in the reference.
"""

from __future__ import annotations

from typing import Optional, Union

import jax.numpy as jnp
import numpy as np

from ..core import factories, fusion, types
from ..core.base import BaseEstimator, RegressionMixin
from ..core.dndarray import DNDarray

__all__ = ["Lasso"]

_SWEEP_CACHE: dict = {}


def _cd_sweep_fn(phys_shape, n: int, comm, fused=None):
    """Cached jitted coordinate sweep; ``lam_n`` is traced so refits with
    different regularization reuse the compilation.

    ``fused=None`` is the legacy program ``(x, y, theta, lam_n) ->
    theta`` (today's dispatch, bitwise; the host differences thetas for
    the convergence check). ``fused=(quant_key, chunk_key, hier_key)``
    builds the tape-compiled sibling ``-> (theta, diff)``: the
    convergence delta moves INTO the program and ``theta`` is DONATED,
    so a fit sweep is key lookup + one dispatch + one ``float(diff)``
    host read."""
    key = ("cdsweep", tuple(phys_shape), n, comm.cache_key, fused)
    fn = _SWEEP_CACHE.get(key)
    if fn is not None:
        return fn
    import jax
    from ..core._compat import shard_map

    c = phys_shape[0] // comm.size
    mm = phys_shape[1] + 1

    def body(xb, yb, theta, lam_n):
        me = jax.lax.axis_index(comm.axis_name)
        valid = (me * c + jnp.arange(c)) < n
        Xb = jnp.concatenate([jnp.ones((c, 1), jnp.float32), xb], axis=1)
        Xb = jnp.where(valid[:, None], Xb, 0.0)
        yv = jnp.where(valid, yb, 0.0)
        col_sq = jax.lax.psum(jnp.sum(Xb * Xb, axis=0), comm.axis_name)
        resid = yv - Xb @ theta  # local rows of the global residual

        def feat(j, carry):
            th, r = carry
            xj = jax.lax.dynamic_slice(Xb, (0, j), (c, 1))[:, 0]
            # rho = xj . (y - X th + xj th_j) = xj . r + th_j ||xj||^2
            rho = jax.lax.psum(xj @ r, comm.axis_name) + th[j] * col_sq[j]
            new = jnp.where(
                j == 0,
                rho / jnp.maximum(col_sq[0], 1e-30),
                Lasso.soft_threshold(rho, lam_n)
                / jnp.maximum(col_sq[j], 1e-30),
            )
            r = r - xj * (new - th[j])
            return th.at[j].set(new), r

        new_theta, _ = jax.lax.fori_loop(0, mm, feat, (theta, resid))
        if fused is None:
            return new_theta
        return new_theta, jnp.max(jnp.abs(new_theta - theta))

    fn = jax.jit(shard_map(
        body, mesh=comm.mesh,
        in_specs=(comm.spec(2, 0), comm.spec(1, 0), comm.spec(1, None),
                  comm.spec(0, None)),
        out_specs=(comm.spec(1, None) if fused is None
                   else (comm.spec(1, None), comm.spec(0, None))),
        check_vma=False),
        donate_argnums=(2,) if fused is not None else ())
    _SWEEP_CACHE[key] = fn
    return fn


def _cd_sweep_eager(n: int, mm: int):
    """The same coordinate sweep dispatched op-by-op (unjitted jnp,
    GSPMD collectives, python feature loop — the reference's controller
    loop shape): the ``fit.step.dispatch`` degrade path. Returns the
    fused-step tuple ``(theta, diff)``."""

    def sweep(xp, yp, theta, lam_n):
        rows = xp.shape[0]
        valid = jnp.arange(rows) < n
        X = jnp.concatenate([jnp.ones((rows, 1), jnp.float32), xp], axis=1)
        X = jnp.where(valid[:, None], X, 0.0)
        yv = jnp.where(valid, yp, 0.0)
        col_sq = jnp.sum(X * X, axis=0)
        r = yv - X @ theta
        th = theta
        for j in range(mm):
            xj = X[:, j]
            rho = xj @ r + th[j] * col_sq[j]
            if j == 0:
                new = rho / jnp.maximum(col_sq[0], 1e-30)
            else:
                new = (Lasso.soft_threshold(rho, lam_n)
                       / jnp.maximum(col_sq[j], 1e-30))
            r = r - xj * (new - th[j])
            th = th.at[j].set(new)
        return th, jnp.max(jnp.abs(th - theta))

    return sweep


class Lasso(RegressionMixin, BaseEstimator):
    """L1-regularized linear regression via coordinate descent
    (reference ``lasso.py:15``)."""

    def __init__(self, lam: float = 0.1, max_iter: int = 100, tol: float = 1e-6):
        self.__lam = lam
        self.max_iter = max_iter
        self.tol = tol
        self.__theta = None
        self.n_iter = None

    @property
    def coef_(self):
        return None if self.__theta is None else self.__theta[1:]

    @property
    def intercept_(self):
        return None if self.__theta is None else self.__theta[:1]

    @property
    def lam(self):
        return self.__lam

    @lam.setter
    def lam(self, arg):
        self.__lam = arg

    @property
    def theta(self):
        return self.__theta

    @staticmethod
    def soft_threshold(rho, lam):
        """Soft-thresholding operator (reference ``lasso.py:73``)."""
        return jnp.sign(rho) * jnp.maximum(jnp.abs(rho) - lam, 0.0)

    @staticmethod
    def rmse(gt, yest):
        """Root mean squared error (reference ``lasso.py:84``)."""
        return float(jnp.sqrt(jnp.mean((gt - yest) ** 2)))

    def fit(self, x: DNDarray, y: DNDarray) -> "Lasso":
        """Coordinate-descent fit (reference ``lasso.py:90-176``).

        Sample-split data stays sharded: one jitted shard_map program runs a
        full coordinate sweep — per feature, the rho/normalizer inner
        products are local partials merged with psum (the reference's
        distributed GEMVs), with the residual carried incrementally.
        theta (m+1 values) is the only replicated state."""
        if not isinstance(x, DNDarray) or not isinstance(y, DNDarray):
            raise TypeError("x and y need to be DNDarrays")
        if x.ndim != 2:
            raise ValueError("x needs to be 2-dimensional (n_samples, n_features)")
        import jax
        from ..core._compat import shard_map

        n, m = x.shape
        mm = m + 1
        lam_n = self.__lam * n

        if x.split == 0 and x.comm.size > 1 and n > 0:
            comm = x.comm
            if isinstance(y, DNDarray) and (y.split != 0 or
                                            y.larray.shape[0] != x.larray.shape[0]):
                y = y.resplit(0)
            xp = x.larray.astype(jnp.float32)
            yp = y.larray.reshape(-1).astype(jnp.float32)
            lam_j = jnp.asarray(lam_n, jnp.float32)

            theta = jnp.zeros((mm,), jnp.float32)
            it = 0
            if fusion.fit_enabled():
                # tape-compiled sweep: theta DONATED, the convergence
                # delta computed in-program — one dispatch + one host
                # read per sweep (fit.step.dispatch degrades to the
                # eager python-loop sweep)
                eager = _cd_sweep_eager(n, mm)
                for it in range(1, self.max_iter + 1):
                    theta, diff = fusion.fit_step_call(
                        ("lasso.sweep", xp.shape, n, comm.cache_key),
                        lambda qk, ck, hk: _cd_sweep_fn(
                            xp.shape, n, comm, fused=(qk, ck, hk)),
                        (xp, yp, theta, lam_j), eager)
                    if float(diff) < self.tol:
                        break
            else:
                sweep = _cd_sweep_fn(xp.shape, n, comm)
                for it in range(1, self.max_iter + 1):
                    new_theta = sweep(xp, yp, theta, lam_j)
                    diff = float(jnp.max(jnp.abs(new_theta - theta)))
                    theta = new_theta
                    if diff < self.tol:
                        break
            self.n_iter = it
            self.__theta = factories.array(
                np.asarray(theta).reshape(-1, 1), dtype=types.float32,
                comm=x.comm)
            return self

        yl = y._logical().reshape(-1).astype(jnp.float32)
        # prepend intercept column
        xl = x._logical().astype(jnp.float32)
        n, m = xl.shape
        X = jnp.concatenate([jnp.ones((n, 1), jnp.float32), xl], axis=1)
        theta = jnp.zeros((mm,), jnp.float32)
        col_sq = jnp.sum(X * X, axis=0)  # feature normalizers

        @jax.jit
        def sweep(theta):
            def body(j, th):
                pred = X @ th
                resid = yl - pred + X[:, j] * th[j]
                rho = X[:, j] @ resid
                new = jnp.where(
                    j == 0,
                    rho / jnp.maximum(col_sq[0], 1e-30),  # intercept: no penalty
                    Lasso.soft_threshold(rho, lam_n) / jnp.maximum(col_sq[j], 1e-30),
                )
                return th.at[j].set(new)

            return jax.lax.fori_loop(0, mm, body, theta)

        it = 0
        for it in range(1, self.max_iter + 1):
            new_theta = sweep(theta)
            diff = float(jnp.max(jnp.abs(new_theta - theta)))
            theta = new_theta
            if diff < self.tol:
                break

        self.n_iter = it
        self.__theta = factories.array(
            np.asarray(theta).reshape(-1, 1), dtype=types.float32, comm=x.comm
        )
        return self

    def predict(self, x: DNDarray) -> DNDarray:
        """Linear prediction (reference ``lasso.py:180``): shard-local rows
        against the replicated theta."""
        if self.__theta is None:
            raise RuntimeError("fit needs to be called before predict")
        th = self.__theta._logical().reshape(-1)
        if x.split == 0 and x.comm.size > 1:
            xp = x.larray.astype(jnp.float32)
            pred = th[0] + xp @ th[1:]
            return DNDarray(
                pred.reshape(-1, 1), (x.shape[0], 1), types.float32, 0,
                x.device, x.comm)
        xl = x._logical().astype(jnp.float32)
        n = xl.shape[0]
        X = jnp.concatenate([jnp.ones((n, 1), jnp.float32), xl], axis=1)
        pred = X @ th
        return DNDarray.from_logical(pred.reshape(-1, 1), x.split, x.device, x.comm)
