"""Lasso regression (reference ``heat/regression/lasso.py``).

Coordinate descent with soft thresholding (reference ``lasso.py:90-176``):
the per-feature loop runs on the controller, each sweep's matvecs are
distributed GEMMs with GSPMD psum. Feature count is the loop bound exactly
as in the reference.
"""

from __future__ import annotations

from typing import Optional, Union

import jax.numpy as jnp
import numpy as np

from ..core import factories, types
from ..core.base import BaseEstimator, RegressionMixin
from ..core.dndarray import DNDarray

__all__ = ["Lasso"]


class Lasso(RegressionMixin, BaseEstimator):
    """L1-regularized linear regression via coordinate descent
    (reference ``lasso.py:15``)."""

    def __init__(self, lam: float = 0.1, max_iter: int = 100, tol: float = 1e-6):
        self.__lam = lam
        self.max_iter = max_iter
        self.tol = tol
        self.__theta = None
        self.n_iter = None

    @property
    def coef_(self):
        return None if self.__theta is None else self.__theta[1:]

    @property
    def intercept_(self):
        return None if self.__theta is None else self.__theta[:1]

    @property
    def lam(self):
        return self.__lam

    @lam.setter
    def lam(self, arg):
        self.__lam = arg

    @property
    def theta(self):
        return self.__theta

    @staticmethod
    def soft_threshold(rho, lam):
        """Soft-thresholding operator (reference ``lasso.py:73``)."""
        return jnp.sign(rho) * jnp.maximum(jnp.abs(rho) - lam, 0.0)

    @staticmethod
    def rmse(gt, yest):
        """Root mean squared error (reference ``lasso.py:84``)."""
        return float(jnp.sqrt(jnp.mean((gt - yest) ** 2)))

    def fit(self, x: DNDarray, y: DNDarray) -> "Lasso":
        """Coordinate-descent fit (reference ``lasso.py:90-176``)."""
        if not isinstance(x, DNDarray) or not isinstance(y, DNDarray):
            raise TypeError("x and y need to be DNDarrays")
        if x.ndim != 2:
            raise ValueError("x needs to be 2-dimensional (n_samples, n_features)")
        yl = y._logical().reshape(-1).astype(jnp.float32)
        # prepend intercept column
        xl = x._logical().astype(jnp.float32)
        n, m = xl.shape
        X = jnp.concatenate([jnp.ones((n, 1), jnp.float32), xl], axis=1)
        mm = m + 1
        theta = jnp.zeros((mm,), jnp.float32)
        col_sq = jnp.sum(X * X, axis=0)  # feature normalizers

        lam_n = self.__lam * n

        import jax

        @jax.jit
        def sweep(theta):
            def body(j, th):
                pred = X @ th
                resid = yl - pred + X[:, j] * th[j]
                rho = X[:, j] @ resid
                new = jnp.where(
                    j == 0,
                    rho / jnp.maximum(col_sq[0], 1e-30),  # intercept: no penalty
                    Lasso.soft_threshold(rho, lam_n) / jnp.maximum(col_sq[j], 1e-30),
                )
                return th.at[j].set(new)

            return jax.lax.fori_loop(0, mm, body, theta)

        it = 0
        for it in range(1, self.max_iter + 1):
            new_theta = sweep(theta)
            diff = float(jnp.max(jnp.abs(new_theta - theta)))
            theta = new_theta
            if diff < self.tol:
                break

        self.n_iter = it
        self.__theta = factories.array(
            np.asarray(theta).reshape(-1, 1), dtype=types.float32, comm=x.comm
        )
        return self

    def predict(self, x: DNDarray) -> DNDarray:
        """Linear prediction (reference ``lasso.py:180``)."""
        if self.__theta is None:
            raise RuntimeError("fit needs to be called before predict")
        xl = x._logical().astype(jnp.float32)
        n = xl.shape[0]
        X = jnp.concatenate([jnp.ones((n, 1), jnp.float32), xl], axis=1)
        pred = X @ self.__theta._logical().reshape(-1)
        return DNDarray.from_logical(pred.reshape(-1, 1), x.split, x.device, x.comm)
