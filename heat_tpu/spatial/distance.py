"""Pairwise distance matrices (reference ``heat/spatial/distance.py``).

The reference's ``_dist`` (``distance.py:209-494``) is a systolic **ring**:
each iteration sends the moving block to ``(rank+iter) % size`` and computes
one local tile (``:280-362``) — the exact communication skeleton of ring
attention. The TPU-native version is a ``shard_map`` over the mesh whose body
unrolls the ring as ``size`` ppermute steps; XLA overlaps the permute DMA
with the tile GEMM (double buffering), and the tile itself is a
matmul-expansion on the MXU.

Replicated-``Y`` inputs (the KMeans inner loop) skip the ring entirely: one
local GEMM tile per shard, zero communication — same as the reference's
replicated fast path.
"""

from __future__ import annotations

from typing import Callable, Optional

import numpy as np

import jax
import jax.numpy as jnp
from ..core._compat import shard_map

from ..core import types
from ..core.dndarray import DNDarray
from ..core.pallas_kernels import cdist_tile, pallas_enabled

__all__ = ["cdist", "manhattan", "rbf"]

# cache of jitted ring kernels keyed by (shapes, dtype, metric, comm key)
_RING_CACHE: dict = {}


def _l2_tile(x, y, expand: bool, sqrt: bool, keep_acc: bool = False):
    """One (tile_x, tile_y) block of pairwise L2 distances (squared when
    ``sqrt=False`` — the KMeans/rbf form that skips the root). Half
    precision keeps bf16 HBM/MXU inputs but accumulates in f32
    (``types.accumulation_dtype``); the result casts back to the input
    dtype unless ``keep_acc`` (rbf applies exp before narrowing)."""
    acc = types.accumulation_dtype(x.dtype)
    out_dt = acc if keep_acc else x.dtype
    if expand:
        if pallas_enabled():
            # fused Pallas tile: norms + MXU GEMM (+ sqrt) in one VMEM
            # pass, accumulated in f32; rbf (keep_acc) gets the f32
            # output so the exp sees unrounded distances
            return cdist_tile(x, y, sqrt=sqrt, out_dtype=str(out_dt))
        # |x-y|² = |x|² + |y|² - 2·x·yᵀ — the GEMM form (MXU)
        xf, yf = x.astype(acc), y.astype(acc)
        x2 = jnp.sum(xf * xf, axis=1, keepdims=True)
        y2 = jnp.sum(yf * yf, axis=1, keepdims=True).T
        xy = jax.lax.dot_general(
            x, y, dimension_numbers=(((1,), (1,)), ((), ())),
            preferred_element_type=acc)
        d2 = jnp.maximum(x2 + y2 - 2.0 * xy, 0.0)
        return (jnp.sqrt(d2) if sqrt else d2).astype(out_dt)
    diff = x.astype(acc)[:, None, :] - y.astype(acc)[None, :, :]
    d2 = jnp.sum(diff * diff, axis=-1)
    return (jnp.sqrt(d2) if sqrt else d2).astype(out_dt)


def _euclidean_tile(x, y, expand: bool):
    return _l2_tile(x, y, expand, sqrt=True)


def _manhattan_tile(x, y, expand: bool):
    acc = types.accumulation_dtype(x.dtype)
    diff = jnp.abs(x.astype(acc)[:, None, :] - y.astype(acc)[None, :, :])
    return jnp.sum(diff, axis=-1).astype(x.dtype)


def _gaussian_tile(sigma: float):
    def tile(x, y, expand: bool):
        # exp runs on the f32-accumulated d2 — rounding d2 to bf16 first
        # would put ~20% error on the kernel value at large exponents
        d2 = _l2_tile(x, y, expand, sqrt=False, keep_acc=True)
        return jnp.exp(-d2 / (2.0 * sigma * sigma)).astype(x.dtype)

    return tile


def _dist(X: DNDarray, Y: Optional[DNDarray], tile_fn: Callable, expand: bool, metric_key=("euclidean",)) -> DNDarray:
    """Distance-matrix driver (reference ``_dist``, ``distance.py:209``)."""
    if not isinstance(X, DNDarray):
        raise TypeError(f"X must be a DNDarray, got {type(X)}")
    if X.ndim != 2:
        raise NotImplementedError(f"X must be 2-dimensional, got {X.ndim}")

    symmetric = Y is None
    if Y is None:
        Y = X
    if not isinstance(Y, DNDarray):
        raise TypeError(f"Y must be a DNDarray, got {type(Y)}")
    if Y.ndim != 2:
        raise NotImplementedError(f"Y must be 2-dimensional, got {Y.ndim}")
    if X.shape[1] != Y.shape[1]:
        raise ValueError(f"feature dimensions differ: {X.shape[1]} != {Y.shape[1]}")

    promoted = types.promote_types(X.dtype, Y.dtype)
    if types.heat_type_is_exact(promoted):
        promoted = types.float32
    jdt = promoted.jax_type()
    n, m = X.shape[0], Y.shape[0]
    comm = X.comm

    if X.split is None and Y.split is None:
        d = tile_fn(X._logical().astype(jdt), Y._logical().astype(jdt), expand)
        return DNDarray.from_logical(d, None, X.device, comm)

    if X.split == 1 or Y.split == 1:
        X = X.resplit(0) if X.split == 1 else X
        Y = Y.resplit(0) if Y.split == 1 else Y

    if X.split is None and Y.split == 0:
        # compute the transposed problem with the fast row-split path
        return _dist(Y, X, tile_fn, expand, metric_key).T

    # X.split == 0 from here
    if Y.split is None:
        # local tiles only (KMeans inner loop): one GEMM per shard
        fn = _local_kernel(X, Y, tile_fn, expand, jdt, comm, metric_key)
        d_phys = fn(X.larray, Y.larray)
        return DNDarray(d_phys, (n, m), promoted, 0, X.device, comm)

    # ring: X stationary, Y circulates (reference ``distance.py:280-362``)
    fn = _ring_kernel(X, Y, tile_fn, expand, jdt, comm, metric_key)
    d_phys = fn(X.larray, Y.larray)
    return DNDarray(d_phys, (n, m), promoted, 0, X.device, comm)


def _local_kernel(X, Y, tile_fn, expand, jdt, comm, metric_key):
    key = (
        "local", X.larray.shape, Y.larray.shape, str(jdt), metric_key, expand,
        comm.cache_key, pallas_enabled(),
    )
    fn = _RING_CACHE.get(key)
    if fn is None:
        out_sharding = comm.sharding(2, 0)

        def _go(xp, yp):
            return tile_fn(xp.astype(jdt), yp.astype(jdt), expand)

        fn = jax.jit(_go, out_shardings=out_sharding)
        _RING_CACHE[key] = fn
    return fn


def _ring_kernel(X, Y, tile_fn, expand, jdt, comm, metric_key):
    """shard_map ring over the mesh: size unrolled ppermute+tile steps."""
    size = comm.size
    m = Y.shape[0]
    c_y = Y.larray.shape[0] // size
    m_pad = Y.larray.shape[0]
    key = (
        "ring", X.larray.shape, Y.larray.shape, str(jdt), metric_key, expand,
        comm.cache_key, pallas_enabled(),
    )
    fn = _RING_CACHE.get(key)
    if fn is None:
        spec = comm.spec(2, 0)
        axis = comm.axis_name
        perm = [(j, (j + 1) % size) for j in range(size)]

        def body(x_blk, y_blk):
            x_blk = x_blk.astype(jdt)
            y_cur = y_blk.astype(jdt)
            if size == 1:
                # single-device (the bench configuration): the tile IS the
                # whole output — the zeros buffer + dynamic_update_slice +
                # final slice of the general ring would each risk a full
                # extra pass over the n*m matrix (PERF_r04.md §cdist)
                return tile_fn(x_blk, y_cur, expand)[:, :m]
            me = jax.lax.axis_index(axis)
            out = jnp.zeros((x_blk.shape[0], m_pad), jdt)
            for step in range(size):
                # block currently held came from device (me - step) % size
                src = (me - step) % size
                tile = tile_fn(x_blk, y_cur, expand)
                zero = jnp.zeros((), src.dtype)
                out = jax.lax.dynamic_update_slice(out, tile, (zero, src * c_y))
                if step != size - 1:
                    y_cur = jax.lax.ppermute(y_cur, axis, perm)
            return out[:, :m]  # identity slice when m_pad == m (XLA elides)

        sm = shard_map(
            body, mesh=comm.mesh, in_specs=(spec, spec), out_specs=spec, check_vma=False
        )
        fn = jax.jit(sm)
        _RING_CACHE[key] = fn
    return fn


def cdist(X: DNDarray, Y: Optional[DNDarray] = None, quadratic_expansion: bool = False) -> DNDarray:
    """Euclidean distance matrix (reference ``cdist``, ``distance.py:136``)."""
    return _dist(X, Y, _euclidean_tile, quadratic_expansion, ("euclidean",))


def manhattan(X: DNDarray, Y: Optional[DNDarray] = None, expand: bool = False) -> DNDarray:
    """Manhattan distance matrix (reference ``manhattan``, ``distance.py:186``)."""
    return _dist(X, Y, _manhattan_tile, False, ("manhattan",))


def rbf(
    X: DNDarray,
    Y: Optional[DNDarray] = None,
    sigma: float = 1.0,
    quadratic_expansion: bool = False,
) -> DNDarray:
    """Gaussian (RBF) kernel matrix (reference ``rbf``, ``distance.py:159``)."""
    return _dist(X, Y, _gaussian_tile(sigma), quadratic_expansion, ("rbf", float(sigma)))
