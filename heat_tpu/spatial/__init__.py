"""Pairwise spatial distances (reference ``heat/spatial/``)."""

from .distance import *
from . import distance
