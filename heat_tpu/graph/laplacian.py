"""Graph Laplacian construction (reference ``heat/graph/laplacian.py:73-141``).

Every assembly step is row-local on the physical shards: the degree vector
(one row-sum, GSPMD psum over the column axis) replicates — O(n) floats —
and thresholding, diagonal writes, and the D^-1/2 scaling apply per shard
against the global row positions. The n x n similarity matrix itself is
never gathered.
"""

from __future__ import annotations

from typing import Callable, Optional

import jax
import jax.numpy as jnp

from ..core import arithmetics, factories, types
from ..core.dndarray import DNDarray

__all__ = ["Laplacian"]


def _row_positions(A: DNDarray):
    """Global row index of every physical row (split=0) plus the row-valid
    mask; for replicated A this is just arange."""
    rows = A.larray.shape[0]
    gpos = jnp.arange(rows)
    return gpos, gpos < A.shape[0]


def _set_diag(phys, gpos, value):
    """Write ``value`` at the global diagonal positions of a row-split
    (or replicated) physical block matrix."""
    n = phys.shape[1]
    col = jnp.clip(gpos, 0, n - 1)
    onehot = col[:, None] == jnp.arange(n)[None, :]
    ok = (gpos < n)[:, None] & onehot
    return jnp.where(ok, jnp.asarray(value, phys.dtype), phys)


class Laplacian:
    """Adjacency-from-similarity + Laplacian assembly (reference ``laplacian.py:14``).

    Parameters follow the reference: ``similarity`` is a callable producing a
    pairwise similarity DNDarray (e.g. ``ht.spatial.rbf``); connectivity is
    thresholded either by ``eps``-neighborhood ("eNeighbour") or (gathered)
    k-nearest neighbors; ``definition`` selects simple or symmetrically
    normalized L.
    """

    def __init__(
        self,
        similarity: Callable,
        definition: str = "norm_sym",
        mode: str = "fully_connected",
        threshold_key: str = "upper",
        threshold_value: float = 1.0,
        neighbours: int = 10,
    ):
        self.similarity_metric = similarity
        if definition not in ("simple", "norm_sym"):
            raise NotImplementedError(
                "Only simple and normalized symmetric graph laplacians are supported"
            )
        self.definition = definition
        if mode not in ("fully_connected", "eNeighbour"):
            raise NotImplementedError(
                "Only fully_connected and eNeighbour modes are supported"
            )
        self.mode = mode
        if threshold_key not in ("upper", "lower"):
            raise ValueError(f"threshold_key must be 'upper' or 'lower', got {threshold_key}")
        self.epsilon = (threshold_key, threshold_value)
        self.neighbours = neighbours

    @staticmethod
    def _degree_replicated(A: DNDarray):
        """Degree vector as a replicated (n,) jnp array — O(n) floats, the
        only cross-device product of the assembly."""
        degree = arithmetics.sum(A, axis=1)
        return degree.resplit(None)._logical()

    def _normalized_symmetric_L(self, A: DNDarray) -> DNDarray:
        """L_sym = I - D^-1/2 A D^-1/2 (reference ``laplacian.py:73``)."""
        d = self._degree_replicated(A)
        inv_sqrt = jnp.where(d > 0, 1.0 / jnp.sqrt(d), 0.0)
        gpos, _ = _row_positions(A)
        row_scale = jnp.where(gpos < A.shape[0],
                              inv_sqrt[jnp.clip(gpos, 0, A.shape[0] - 1)], 0.0)
        L = -A.larray * row_scale[:, None] * inv_sqrt[None, :]
        L = _set_diag(L, gpos, 1.0)
        return DNDarray(L, A.gshape, types.canonical_heat_type(L.dtype),
                        A.split, A.device, A.comm)

    def _simple_L(self, A: DNDarray) -> DNDarray:
        """L = D - A (reference ``laplacian.py:105``): the diagonal degree
        lands on each row's owner; off-diagonal is -A shard-locally."""
        d = self._degree_replicated(A)
        gpos, _ = _row_positions(A)
        n = A.shape[0]
        dg = jnp.where(gpos < n, d[jnp.clip(gpos, 0, n - 1)], 0.0)
        col = jnp.clip(gpos, 0, n - 1)
        onehot = (col[:, None] == jnp.arange(n)[None, :]) & (gpos < n)[:, None]
        L = jnp.where(onehot, dg[:, None], 0.0) - A.larray
        return DNDarray(L, A.gshape, types.canonical_heat_type(L.dtype),
                        A.split, A.device, A.comm)

    def construct(self, X: DNDarray) -> DNDarray:
        """Build L from data (reference ``laplacian.py:118-141``)."""
        S = self.similarity_metric(X)
        if S.split not in (None, 0):
            S = S.resplit(0)
        gpos, _ = _row_positions(S)
        phys = S.larray
        if self.mode == "eNeighbour":
            key, value = self.epsilon
            if key == "upper":
                phys = jnp.where(phys < value, phys, 0.0)
            else:
                phys = jnp.where(phys > value, phys, 0.0)
        A = _set_diag(phys, gpos, 0.0)
        S = DNDarray(A, S.gshape, S.dtype, S.split, S.device, S.comm)
        if self.definition == "simple":
            return self._simple_L(S)
        return self._normalized_symmetric_L(S)
