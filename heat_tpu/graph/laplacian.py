"""Graph Laplacian construction (reference ``heat/graph/laplacian.py:73-141``)."""

from __future__ import annotations

from typing import Callable, Optional

import jax.numpy as jnp

from ..core import arithmetics, factories, types
from ..core.dndarray import DNDarray

__all__ = ["Laplacian"]


class Laplacian:
    """Adjacency-from-similarity + Laplacian assembly (reference ``laplacian.py:14``).

    Parameters follow the reference: ``similarity`` is a callable producing a
    pairwise similarity DNDarray (e.g. ``ht.spatial.rbf``); connectivity is
    thresholded either by ``eps``-neighborhood ("eNeighbour") or (gathered)
    k-nearest neighbors; ``definition`` selects simple or symmetrically
    normalized L.
    """

    def __init__(
        self,
        similarity: Callable,
        definition: str = "norm_sym",
        mode: str = "fully_connected",
        threshold_key: str = "upper",
        threshold_value: float = 1.0,
        neighbours: int = 10,
    ):
        self.similarity_metric = similarity
        if definition not in ("simple", "norm_sym"):
            raise NotImplementedError(
                "Only simple and normalized symmetric graph laplacians are supported"
            )
        self.definition = definition
        if mode not in ("fully_connected", "eNeighbour"):
            raise NotImplementedError(
                "Only fully_connected and eNeighbour modes are supported"
            )
        self.mode = mode
        if threshold_key not in ("upper", "lower"):
            raise ValueError(f"threshold_key must be 'upper' or 'lower', got {threshold_key}")
        self.epsilon = (threshold_key, threshold_value)
        self.neighbours = neighbours

    def _normalized_symmetric_L(self, A: DNDarray) -> DNDarray:
        """L_sym = I - D^-1/2 A D^-1/2 (reference ``laplacian.py:73``)."""
        degree = arithmetics.sum(A, axis=1)
        logical_A = A._logical()
        d = degree._logical()
        inv_sqrt = jnp.where(d > 0, 1.0 / jnp.sqrt(d), 0.0)
        L = -logical_A * inv_sqrt[:, None] * inv_sqrt[None, :]
        n = A.shape[0]
        L = L.at[jnp.arange(n), jnp.arange(n)].set(1.0)
        return DNDarray.from_logical(L, A.split, A.device, A.comm)

    def _simple_L(self, A: DNDarray) -> DNDarray:
        """L = D - A (reference ``laplacian.py:105``)."""
        degree = arithmetics.sum(A, axis=1)
        logical_A = A._logical()
        L = jnp.diag(degree._logical()) - logical_A
        return DNDarray.from_logical(L, A.split, A.device, A.comm)

    def construct(self, X: DNDarray) -> DNDarray:
        """Build L from data (reference ``laplacian.py:118-141``)."""
        S = self.similarity_metric(X)
        if self.mode == "eNeighbour":
            key, value = self.epsilon
            logical = S._logical()
            if key == "upper":
                A = jnp.where(logical < value, logical, 0.0)
            else:
                A = jnp.where(logical > value, logical, 0.0)
            n = S.shape[0]
            A = A.at[jnp.arange(n), jnp.arange(n)].set(0.0)
            S = DNDarray.from_logical(A, S.split, S.device, S.comm)
        else:
            logical = S._logical()
            n = S.shape[0]
            A = logical.at[jnp.arange(n), jnp.arange(n)].set(0.0)
            S = DNDarray.from_logical(A, S.split, S.device, S.comm)
        if self.definition == "simple":
            return self._simple_L(S)
        return self._normalized_symmetric_L(S)
