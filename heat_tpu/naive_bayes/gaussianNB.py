"""Gaussian naive Bayes (reference ``heat/naive_bayes/gaussianNB.py``).

Distributed per-class mean/variance accumulation (reference ``:131-199``)
expressed as masked one-hot GEMMs + GSPMD psum; ``partial_fit`` keeps the
reference's incremental mean/var update formulas.
"""

from __future__ import annotations

from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np

from ..core import factories, fusion, types
from ..core.base import BaseEstimator, ClassificationMixin
from ..core.dndarray import DNDarray

__all__ = ["GaussianNB"]


def _jll_body(xl, means, variances, log_prior):
    """Per-class joint log likelihood, (n, k): the predict-assign hot
    math. Module-level so the compiled and eager paths share ONE
    definition (unjitted it is today's inline op-by-op dispatch and the
    ``fit.step.dispatch`` degrade path)."""
    # (n, k): -0.5 * sum(log(2πσ²)) - 0.5 * sum((x-μ)²/σ²)
    const = -0.5 * jnp.sum(jnp.log(2.0 * jnp.pi * variances), axis=1)  # (k,)
    diff = xl[:, None, :] - means[None, :, :]
    mahal = -0.5 * jnp.sum(diff * diff / variances[None, :, :], axis=2)
    return log_prior[None, :] + const[None, :] + mahal


# GSPMD places the (collective-free) sharded row math; jit re-specializes
# per avals, and fit_step_call memoizes per signature — no extra cache
# layer needed
_JLL_JIT = jax.jit(_jll_body)


class GaussianNB(ClassificationMixin, BaseEstimator):
    """Gaussian naive Bayes classifier (reference ``gaussianNB.py:20``)."""

    def __init__(self, priors=None, var_smoothing: float = 1e-9):
        self.priors = priors
        self.var_smoothing = var_smoothing
        self.classes_ = None
        self.theta_ = None
        self.var_ = None
        self.class_count_ = None
        self.class_prior_ = None
        self.epsilon_ = None

    def fit(self, x: DNDarray, y: DNDarray, sample_weight=None) -> "GaussianNB":
        """Full fit (reference ``gaussianNB.py:102``)."""
        self.classes_ = None
        self.theta_ = None
        return self.partial_fit(x, y, classes=None, sample_weight=sample_weight)

    def partial_fit(self, x: DNDarray, y: DNDarray, classes=None, sample_weight=None) -> "GaussianNB":
        """Incremental fit (reference ``gaussianNB.py:200``).

        The per-class moment accumulation runs on the physical shards: a
        validity-masked one-hot GEMM whose contraction over the sample axis
        is psum'd by GSPMD (the reference's Allreduce of per-rank moments,
        ``:131-199``) — the data is never gathered. Class discovery on a
        split label vector uses the distributed ``unique``."""
        if not isinstance(x, DNDarray) or not isinstance(y, DNDarray):
            raise TypeError("x and y need to be DNDarrays")
        if y.shape[0] != x.shape[0]:
            raise ValueError(
                f"y has {y.shape[0]} samples but x has {x.shape[0]}"
            )
        if x.split not in (None, 0):
            x = x.resplit(0)
        if y.split != x.split:
            y = y.resplit(x.split)
        n = x.shape[0]
        rowvalid = (x.valid_mask()[:, 0] if x.ndim > 1 else x.valid_mask()) \
            if x.split == 0 else jnp.ones((x.larray.shape[0],), jnp.bool_)
        # padding discipline: any non-finite garbage in the pad rows would
        # poison the moment GEMMs via 0 * inf = NaN (review finding)
        xl = jnp.where(rowvalid[:, None] if x.ndim > 1 else rowvalid,
                       x.larray, 0).astype(jnp.float64)
        yl = y.larray.reshape(-1)

        if classes is not None:
            class_vals = np.asarray(
                classes.numpy() if isinstance(classes, DNDarray) else classes
            )
        elif self.classes_ is not None:
            class_vals = np.asarray(self.classes_.numpy())
        else:
            from ..core.manipulations import unique as ht_unique

            class_vals = np.asarray(ht_unique(y, sorted=True).numpy())
        k = len(class_vals)
        classes_j = jnp.asarray(class_vals)

        onehot = ((yl[:, None] == classes_j[None, :]) & rowvalid[:, None]
                  ).astype(jnp.float64)  # (n_phys, k)
        if sample_weight is not None:
            if isinstance(sample_weight, DNDarray):
                w = sample_weight.resplit(x.split).larray
            else:
                w = DNDarray.from_logical(
                    jnp.asarray(sample_weight).reshape(-1), x.split,
                    x.device, x.comm).larray
            onehot = onehot * jnp.where(rowvalid, w.reshape(-1), 0
                                        ).reshape(-1, 1)
        counts = jnp.sum(onehot, axis=0)  # (k,) — GSPMD psum
        sums = onehot.T @ xl  # (k, d) — contraction over the sharded axis
        means = sums / jnp.maximum(counts, 1e-30)[:, None]
        sq = onehot.T @ (xl * xl)
        variances = sq / jnp.maximum(counts, 1e-30)[:, None] - means**2

        s1 = jnp.sum(xl, axis=0) / n  # xl is already padding-masked
        s2 = jnp.sum(xl * xl, axis=0) / n
        eps = self.var_smoothing * float(jnp.max(s2 - s1 * s1))
        if self.theta_ is None:
            new_counts, new_means, new_vars = counts, means, variances
        else:
            # incremental merge (reference update_mean_variance ``:131-199``)
            old_counts = jnp.asarray(self.class_count_.numpy())
            old_means = jnp.asarray(self.theta_.numpy())
            old_vars = jnp.asarray(self.var_.numpy()) - self.epsilon_
            total = old_counts + counts
            new_means = (
                old_means * old_counts[:, None] + means * counts[:, None]
            ) / jnp.maximum(total, 1e-30)[:, None]
            old_ssd = old_vars * old_counts[:, None]
            new_ssd = variances * counts[:, None]
            corr = (
                (old_counts * counts)[:, None]
                / jnp.maximum(total, 1e-30)[:, None]
                * (old_means - means) ** 2
            )
            new_vars = (old_ssd + new_ssd + corr) / jnp.maximum(total, 1e-30)[:, None]
            new_counts = total

        self.epsilon_ = eps
        comm = x.comm
        self.classes_ = factories.array(class_vals, comm=comm)
        self.class_count_ = factories.array(np.asarray(new_counts), comm=comm)
        self.theta_ = factories.array(np.asarray(new_means), comm=comm)
        self.var_ = factories.array(np.asarray(new_vars + eps), comm=comm)
        if self.priors is not None:
            priors = np.asarray(
                self.priors.numpy() if isinstance(self.priors, DNDarray) else self.priors
            )
            if len(priors) != k:
                raise ValueError("Number of priors must match number of classes.")
            if not np.isclose(priors.sum(), 1.0):
                raise ValueError("The sum of the priors should be 1.")
            if (priors < 0).any():
                raise ValueError("Priors must be non-negative.")
            self.class_prior_ = factories.array(priors, comm=comm)
        else:
            total = np.asarray(new_counts).sum()
            self.class_prior_ = factories.array(np.asarray(new_counts) / total, comm=comm)
        return self

    def _joint_log_likelihood(self, x: DNDarray):
        """Per-class joint log likelihood (reference ``gaussianNB.py:391``):
        shard-local rows against the replicated class moments, compiled
        as ONE program per signature through the fit-step engine (the
        predict-assign path; ``HEAT_TPU_FUSION_FIT=0`` restores the
        historic inline op-by-op dispatch). Returns ``(jll_physical, x)``
        with ``x`` normalized to a row split."""
        if x.split not in (None, 0):
            x = x.resplit(0)
        xl = x.larray.astype(jnp.float64)
        means = jnp.asarray(self.theta_.numpy())  # (k, d)
        variances = jnp.asarray(self.var_.numpy())
        priors = jnp.asarray(self.class_prior_.numpy())
        log_prior = jnp.log(priors)
        kk = means.shape[0]
        if fusion.fit_enabled():
            jll = fusion.fit_step_call(
                ("gnb.jll", tuple(xl.shape), kk, str(xl.dtype), x.split),
                lambda qk, ck, hk: _JLL_JIT,
                (xl, means, variances, log_prior), _jll_body)
        else:
            jll = _jll_body(xl, means, variances, log_prior)
        return jll, x

    def logsumexp(self, a, axis=None, b=None, keepdims=False, return_sign=False):
        """Stable log-sum-exp (reference ``gaussianNB.py:407``)."""
        al = a._logical() if isinstance(a, DNDarray) else jnp.asarray(a)
        res = jax_logsumexp(al, axis=axis, keepdims=keepdims)
        return DNDarray.from_logical(res, None, getattr(a, "device", None), getattr(a, "comm", None)) \
            if isinstance(a, DNDarray) else res

    def predict(self, x: DNDarray) -> DNDarray:
        """Class prediction (reference ``gaussianNB.py:360``): argmax per
        shard row, output stays split."""
        jll, xs = self._joint_log_likelihood(x)
        idx = jnp.argmax(jll, axis=1)
        classes = jnp.asarray(self.classes_.numpy())
        return DNDarray(
            classes[idx], (xs.shape[0],),
            types.canonical_heat_type(classes.dtype), xs.split, xs.device,
            xs.comm)

    def predict_log_proba(self, x: DNDarray) -> DNDarray:
        """Log class probabilities (reference ``gaussianNB.py:440``)."""
        jll, xs = self._joint_log_likelihood(x)
        norm = jax_logsumexp(jll, axis=1, keepdims=True)
        res = jll - norm
        return DNDarray(
            res, (xs.shape[0], res.shape[1]),
            types.canonical_heat_type(res.dtype), xs.split, xs.device,
            xs.comm)

    def predict_proba(self, x: DNDarray) -> DNDarray:
        """Class probabilities (reference ``gaussianNB.py:470``)."""
        lp = self.predict_log_proba(x)
        return DNDarray(
            jnp.exp(lp.larray), lp.gshape, lp.dtype, lp.split, lp.device,
            lp.comm)


def jax_logsumexp(a, axis=None, keepdims=False):
    amax = jnp.max(a, axis=axis, keepdims=True)
    out = jnp.log(jnp.sum(jnp.exp(a - amax), axis=axis, keepdims=True)) + amax
    if not keepdims and axis is not None:
        out = jnp.squeeze(out, axis=axis)
    elif not keepdims:
        out = out.reshape(())
    return out
