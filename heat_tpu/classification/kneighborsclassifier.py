"""K-nearest-neighbors classifier (reference
``heat/classification/kneighborsclassifier.py:45-136``).

cdist to the training set (ring or GEMM tiles) → top-k smallest → one-hot
vote, all on-device.
"""

from __future__ import annotations

import jax.numpy as jnp
import numpy as np

from ..core import factories
from ..core.base import BaseEstimator, ClassificationMixin
from ..core.dndarray import DNDarray

__all__ = ["KNeighborsClassifier"]


class KNeighborsClassifier(ClassificationMixin, BaseEstimator):
    """KNN voting classifier (reference ``kneighborsclassifier.py:18``)."""

    def __init__(self, n_neighbors: int = 5):
        self.n_neighbors = n_neighbors
        self.x = None
        self.y = None

    def fit(self, x: DNDarray, y: DNDarray):
        """Store the training set (reference ``:45``)."""
        if not isinstance(x, DNDarray) or not isinstance(y, DNDarray):
            raise TypeError("x and y need to be DNDarrays")
        self.x = x
        self.y = y
        return self

    def predict(self, x: DNDarray) -> DNDarray:
        """Vote among the k nearest training points (reference ``:80-136``).

        The distance matrix stays split over the test rows — the k-nearest
        selection and the vote are per-row local against the replicated
        training labels, so only the winning labels exist per shard."""
        if self.x is None:
            raise RuntimeError("fit needs to be called before predict")
        from ..core import types as _types
        from ..spatial.distance import cdist

        if x.split not in (None, 0):
            x = x.resplit(0)
        d = cdist(x, self.x.resplit(None), quadratic_expansion=True)
        k = self.n_neighbors
        import jax

        # k smallest distances → indices; axis 1 is unsharded, so top_k is
        # shard-local on the physical rows (padding rows produce garbage
        # votes that stay in padding)
        _, idx = jax.lax.top_k(-d.larray, k)  # (n_test_phys, k)
        yl = self.y.resplit(None)._logical().reshape(-1)
        labels = yl[idx]  # (n_test_phys, k)
        classes = jnp.unique(yl)
        votes = jnp.sum(labels[:, :, None] == classes[None, None, :], axis=1)
        winner = classes[jnp.argmax(votes, axis=1)]
        return DNDarray(
            winner, (x.shape[0],), _types.canonical_heat_type(winner.dtype),
            d.split, x.device, x.comm)
