"""K-nearest-neighbors classifier (reference
``heat/classification/kneighborsclassifier.py:45-136``).

cdist to the training set → top-k smallest → vote, all on-device. For a
**split** training set the reference streams it block-by-block through the
systolic ring of ``_dist`` (``heat/spatial/distance.py:280-362``) and merges
per-block results; re-derived here as one shard_map ring program that
circulates (train block, train labels) with ``ppermute`` and carries an
online k-smallest merge of (distance, label) per test row — O(shard) memory,
the training set is never replicated.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np

from ..core import factories, fusion
from ..core.base import BaseEstimator, ClassificationMixin
from ..core.dndarray import DNDarray

__all__ = ["KNeighborsClassifier"]

# jitted ring kernels keyed by (shapes, dtypes, k, comm key)
_RING_CACHE: dict = {}


def _label_sentinel(ldt):
    """Largest value of the label dtype — the tie-break filler."""
    if jnp.issubdtype(ldt, jnp.floating):
        return jnp.asarray(jnp.inf, ldt)
    if jnp.dtype(ldt) == jnp.bool_:
        return jnp.asarray(True, ldt)
    return jnp.asarray(jnp.iinfo(ldt).max, ldt)


def _vote(carry_l, k):
    """Per-row majority vote among the k carried labels with the
    smallest-label tie-break (== the reference's ``argmax`` over votes
    indexed by ascending unique classes, ``kneighborsclassifier.py:117``)."""
    eq = carry_l[:, :, None] == carry_l[:, None, :]
    counts = jnp.sum(eq, axis=1)  # counts[r, j] = #slots equal to label j
    maxc = jnp.max(counts, axis=1, keepdims=True)
    cand = jnp.where(counts == maxc, carry_l, _label_sentinel(carry_l.dtype))
    return jnp.min(cand, axis=1)


def _ring_predict_fn(comm, k, n_train, c_train, jdt, ldt, shapes):
    key = ("knn_ring", k, n_train, shapes, str(jdt), str(ldt), comm.cache_key)
    fn = _RING_CACHE.get(key)
    if fn is not None:
        return fn
    size, axis = comm.size, comm.axis_name
    perm = [(j, (j + 1) % size) for j in range(size)]
    spec2 = comm.spec(2, 0)
    spec1 = comm.spec(1, 0)

    def body(x_blk, y_blk, lab_blk):
        x_blk = x_blk.astype(jdt)
        y_cur = y_blk.astype(jdt)
        lab_cur = lab_blk
        me = jax.lax.axis_index(axis)
        r = x_blk.shape[0]
        carry_d = jnp.full((r, k), jnp.inf, jdt)
        carry_l = jnp.zeros((r, k), ldt)
        x2 = jnp.sum(x_blk * x_blk, axis=1, keepdims=True)
        for step in range(size):
            src = (me - step) % size
            # |x-y|² GEMM tile (MXU), one block of the distance matrix
            y2 = jnp.sum(y_cur * y_cur, axis=1, keepdims=True).T
            tile = jnp.maximum(x2 + y2 - 2.0 * (x_blk @ y_cur.T), 0.0)
            valid = (src * c_train + jnp.arange(c_train)) < n_train
            tile = jnp.where(valid[None, :], tile, jnp.inf)
            alld = jnp.concatenate([carry_d, tile], axis=1)
            alll = jnp.concatenate(
                [carry_l, jnp.broadcast_to(lab_cur[None, :], tile.shape).astype(ldt)],
                axis=1)
            negd, idx = jax.lax.top_k(-alld, k)
            carry_d = -negd
            carry_l = jnp.take_along_axis(alll, idx, axis=1)
            if step != size - 1:
                y_cur = jax.lax.ppermute(y_cur, axis, perm)
                lab_cur = jax.lax.ppermute(lab_cur, axis, perm)
        return _vote(carry_l, k)

    from ..core._compat import shard_map

    sm = shard_map(
        body, mesh=comm.mesh, in_specs=(spec2, spec2, spec1),
        out_specs=spec1, check_vma=False)
    fn = jax.jit(sm)
    _RING_CACHE[key] = fn
    return fn


def _ring_predict_eager(k, n_train, jdt, ldt, block_rows=4096):
    """The predict-assign mathematics dispatched op-by-op: the
    ``fit.step.dispatch`` degrade path of the ring program. The training
    set is consumed in ``block_rows`` blocks with a running top-k merge,
    so the degrade path keeps the ring's bounded memory (never a full
    (n_test, n_train) distance matrix — the configuration the ring
    exists to protect must survive its own fallback). Distance TIES may
    vote differently than the ring's streaming merge (both orders are
    valid k-NN answers); everything else matches."""

    def predict(xl, xtl, ytl):
        xl = xl.astype(jdt)
        xtl = xtl.astype(jdt)
        ytl = ytl.astype(ldt)
        x2 = jnp.sum(xl * xl, axis=1, keepdims=True)
        best_d = jnp.full((xl.shape[0], k), jnp.inf, jdt)
        best_l = jnp.zeros((xl.shape[0], k), ldt)
        for lo in range(0, xtl.shape[0], block_rows):
            blk = xtl[lo:lo + block_rows]
            valid = lo + jnp.arange(blk.shape[0]) < n_train
            y2 = jnp.sum(blk * blk, axis=1)[None, :]
            tile = jnp.maximum(x2 + y2 - 2.0 * (xl @ blk.T), 0.0)
            tile = jnp.where(valid[None, :], tile, jnp.inf)
            lab = jnp.broadcast_to(ytl[lo:lo + block_rows][None, :],
                                   tile.shape)
            # running candidates first: equal-distance ties resolve to
            # the earlier train row, like a whole-set lax.top_k would
            cand_d = jnp.concatenate([best_d, tile], axis=1)
            cand_l = jnp.concatenate([best_l, lab], axis=1)
            neg_d, idx = jax.lax.top_k(-cand_d, k)
            best_d = -neg_d
            best_l = jnp.take_along_axis(cand_l, idx, axis=1)
        return _vote(best_l, k)

    return predict


class KNeighborsClassifier(ClassificationMixin, BaseEstimator):
    """KNN voting classifier (reference ``kneighborsclassifier.py:18``)."""

    def __init__(self, n_neighbors: int = 5):
        self.n_neighbors = n_neighbors
        self.x = None
        self.y = None

    def fit(self, x: DNDarray, y: DNDarray):
        """Store the training set (reference ``:45``)."""
        if not isinstance(x, DNDarray) or not isinstance(y, DNDarray):
            raise TypeError("x and y need to be DNDarrays")
        self.x = x
        self.y = y
        return self

    def predict(self, x: DNDarray) -> DNDarray:
        """Vote among the k nearest training points (reference ``:80-136``).

        Split training sets stream through the ring (one circulating block
        per device, O(shard) memory); replicated training sets take the
        zero-communication local-tile path.
        """
        if self.x is None:
            raise RuntimeError("fit needs to be called before predict")
        from ..core import types as _types

        if x.split not in (None, 0):
            x = x.resplit(0)
        k = self.n_neighbors
        comm = x.comm
        if k > self.x.shape[0]:
            raise ValueError(
                f"n_neighbors={k} exceeds the {self.x.shape[0]} training "
                "points")

        if self.x.split == 0 and comm.size > 1:
            if x.split is None:
                x = x.resplit(0)
            xt = self.x
            yt = self.y if self.y.split == 0 else self.y.resplit(0)
            jdt = jnp.promote_types(x.larray.dtype, xt.larray.dtype)
            if not jnp.issubdtype(jdt, jnp.floating):
                jdt = jnp.dtype(jnp.float32)
            ldt = yt.larray.dtype
            c_train = xt.larray.shape[0] // comm.size
            shapes = (x.larray.shape, xt.larray.shape)
            args = (x.larray, xt.larray, yt.larray.reshape(-1))
            if fusion.fit_enabled():
                # predict-assign through the fit-step engine: program
                # keyed in the fusion cache, fit.step.dispatch degrading
                # to the eager whole-train-set tile
                winner = fusion.fit_step_call(
                    ("knn.ring", k, xt.shape[0], shapes, str(jdt),
                     str(ldt), comm.cache_key),
                    lambda qk, ck, hk: _ring_predict_fn(
                        comm, k, xt.shape[0], c_train, jdt, ldt, shapes),
                    args, _ring_predict_eager(k, xt.shape[0], jdt, ldt))
            else:
                winner = _ring_predict_fn(
                    comm, k, xt.shape[0], c_train, jdt, ldt, shapes)(*args)
            winner = jax.device_put(winner, comm.sharding(1, 0))
            return DNDarray(
                winner, (x.shape[0],), _types.canonical_heat_type(winner.dtype),
                0, x.device, comm)

        from ..spatial.distance import cdist

        d = cdist(x, self.x.resplit(None), quadratic_expansion=True)
        # k smallest distances → indices; axis 1 is unsharded, so top_k is
        # shard-local on the physical rows (padding rows produce garbage
        # votes that stay in padding)
        _, idx = jax.lax.top_k(-d.larray, k)  # (n_test_phys, k)
        yl = self.y.resplit(None)._logical().reshape(-1)
        labels = yl[idx]  # (n_test_phys, k)
        classes = jnp.unique(yl)
        votes = jnp.sum(labels[:, :, None] == classes[None, None, :], axis=1)
        winner = classes[jnp.argmax(votes, axis=1)]
        return DNDarray(
            winner, (x.shape[0],), _types.canonical_heat_type(winner.dtype),
            d.split, x.device, x.comm)
