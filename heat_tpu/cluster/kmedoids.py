"""KMedoids clustering (reference ``heat/cluster/kmedoids.py``).

Reference semantics: after the mean update, each centroid is snapped to the
nearest actual data point of its cluster (``kmedoids.py:10`` — the
"medoid-by-projection" variant, not full PAM). Implemented as a masked
argmin of the distance-to-centroid column per cluster.
"""

from __future__ import annotations

from typing import Optional, Union

import jax.numpy as jnp
import numpy as np

from ..core.dndarray import DNDarray
from ._kcluster import _KCluster

__all__ = ["KMedoids"]


class KMedoids(_KCluster):
    """K-Medoids (snap-to-point Lloyd, reference ``kmedoids.py:10``)."""

    def __init__(
        self,
        n_clusters: int = 8,
        init: Union[str, DNDarray] = "random",
        max_iter: int = 300,
        random_state: Optional[int] = None,
    ):
        from ..spatial.distance import manhattan

        super().__init__(
            metric=lambda x, y: manhattan(x, y),
            n_clusters=n_clusters,
            init=init,
            max_iter=max_iter,
            tol=0.0,
            random_state=random_state,
        )

    def fit(self, x: DNDarray) -> "KMedoids":
        if not isinstance(x, DNDarray):
            raise TypeError(f"input needs to be a DNDarray, but was {type(x)}")
        if x.split not in (None, 0):
            x = x.resplit(0)
        self._initialize_cluster_centers(x)

        k = self.n_clusters
        logical = x._logical().astype(jnp.float32)
        centroids = self._cluster_centers._logical().astype(jnp.float32)

        it = 0
        for it in range(1, self.max_iter + 1):
            d = jnp.sum(jnp.abs(logical[:, None, :] - centroids[None, :, :]), axis=-1)
            labels = jnp.argmin(d, axis=1)
            member = labels[:, None] == jnp.arange(k)[None, :]
            counts = jnp.sum(member, axis=0)
            sums = member.astype(logical.dtype).T @ logical
            means = sums / jnp.maximum(counts, 1)[:, None]
            # snap each mean to the nearest member point (the medoid step)
            d_mean = jnp.sum(jnp.abs(logical[:, None, :] - means[None, :, :]), axis=-1)
            d_mean = jnp.where(member, d_mean, jnp.inf)
            medoid_idx = jnp.argmin(d_mean, axis=0)  # (k,)
            new_centroids = logical[medoid_idx]
            new_centroids = jnp.where((counts > 0)[:, None], new_centroids, centroids)
            shift = float(jnp.sum((new_centroids - centroids) ** 2))
            centroids = new_centroids
            if shift == 0.0:
                break

        self._cluster_centers = DNDarray.from_logical(centroids, None, x.device, x.comm)
        self._labels = DNDarray.from_logical(
            labels, 0 if x.split == 0 else None, x.device, x.comm
        )
        self._n_iter = it
        return self
