"""KMedoids clustering (reference ``heat/cluster/kmedoids.py``).

Manhattan assignment; the centroid update snaps each cluster mean to the
nearest member point (the reference's medoid step). Fully distributed: one
jitted shard_map program per iteration — assignment and per-cluster
mean are local + psum, and the medoid snap is a value-index pmin tournament
(ties break to the lowest global row) whose winning row is broadcast with a
masked psum, the same pivot-row pattern as the distributed Gauss-Jordan.
The data is never gathered.
"""

from __future__ import annotations

from typing import Optional, Union

import jax
import jax.numpy as jnp
import numpy as np
from ..core._compat import shard_map

from ..core.dndarray import DNDarray
from ..core import fusion, types
from ..core._sort import _index_dtype
from ._kcluster import _KCluster

__all__ = ["KMedoids"]

_STEP_CACHE: dict = {}


def _kmedoids_step_fn(phys_shape, k: int, n: int, comm, fused=None):
    """Jitted ``(x_phys, centroids) -> (new_centroids, shift, labels_phys)``.

    ``fused=None`` is the legacy program (today's dispatch, bitwise);
    ``fused=(quant_key, chunk_key, hier_key)`` builds the tape-compiled
    sibling: the float psums (cluster sums, winning medoid rows) route
    through ``fusion.packed_psum`` pinned to the captured codec tuples —
    so they ride the quant/hier/chunk wire codecs — and the carried
    centroids are DONATED."""
    key = ("kmedo", tuple(phys_shape), k, n, comm.cache_key, fused)
    fn = _STEP_CACHE.get(key)
    if fn is not None:
        return fn
    p = comm.size
    c = phys_shape[0] // p
    idt = _index_dtype()

    def _fsum(v):
        if fused is None:
            return jax.lax.psum(v, comm.axis_name)
        qk, ck, hk = fused
        return fusion.packed_psum([v], (comm.axis_name,), quant=qk,
                                  chunks=ck, hier=hk)[0]

    def body(xb, cent):
        me = jax.lax.axis_index(comm.axis_name)
        gpos = me * c + jnp.arange(c, dtype=idt)
        valid = gpos < n
        dist = jnp.sum(jnp.abs(xb[:, None, :] - cent[None, :, :]), axis=-1)
        labels = jnp.argmin(dist, axis=1)
        member = (labels[:, None] == jnp.arange(k)[None, :]) & valid[:, None]
        counts = jax.lax.psum(jnp.sum(member.astype(idt), axis=0),
                              comm.axis_name)
        sums = _fsum(member.astype(xb.dtype).T @ xb)
        means = sums / jnp.maximum(counts, 1).astype(xb.dtype)[:, None]
        # snap to the nearest member point: per-cluster (distance, row) pmin
        d_mean = jnp.sum(jnp.abs(xb[:, None, :] - means[None, :, :]), axis=-1)
        d_mean = jnp.where(member, d_mean, jnp.inf)  # (c, k)
        loc_i = jnp.argmin(d_mean, axis=0)  # (k,)
        loc_v = jnp.take_along_axis(d_mean, loc_i[None, :], axis=0)[0]
        loc_g = gpos[loc_i]
        gmin = jax.lax.pmin(loc_v, comm.axis_name)
        big = jnp.iinfo(idt).max
        g_win = jax.lax.pmin(
            jnp.where(loc_v == gmin, loc_g, jnp.asarray(big, idt)),
            comm.axis_name)  # (k,) lowest global row among ties
        winner = gpos[:, None] == g_win[None, :]  # (c, k)
        medoids = _fsum(
            jnp.einsum("ck,cd->kd", winner.astype(xb.dtype), xb))
        new_cent = jnp.where((counts > 0)[:, None], medoids, cent)
        shift = jnp.sum((new_cent - cent) ** 2)
        return new_cent, shift, labels

    fn = jax.jit(
        shard_map(
            body, mesh=comm.mesh,
            in_specs=(comm.spec(2, 0), comm.spec(2, None)),
            out_specs=(comm.spec(2, None), comm.spec(0, None),
                       comm.spec(1, 0)),
            check_vma=False),
        donate_argnums=(1,) if fused is not None else ())
    _STEP_CACHE[key] = fn
    return fn


def _kmedoids_eager_step(k: int, n: int):
    """The same assignment/medoid-snap mathematics dispatched op-by-op
    (unjitted jnp, GSPMD collectives): the ``fit.step.dispatch`` degrade
    path. ``argmin`` picks the first (lowest global row) minimizer, the
    same tie-break as the compiled value-index pmin tournament."""

    def step(xp, cent):
        gpos = jnp.arange(xp.shape[0])
        valid = gpos < n
        dist = jnp.sum(jnp.abs(xp[:, None, :] - cent[None, :, :]), axis=-1)
        labels = jnp.argmin(dist, axis=1)
        member = (labels[:, None] == jnp.arange(k)[None, :]) & valid[:, None]
        counts = jnp.sum(member, axis=0)
        sums = member.astype(xp.dtype).T @ xp
        means = sums / jnp.maximum(counts, 1).astype(xp.dtype)[:, None]
        d_mean = jnp.sum(jnp.abs(xp[:, None, :] - means[None, :, :]), axis=-1)
        d_mean = jnp.where(member, d_mean, jnp.inf)
        medoid_idx = jnp.argmin(d_mean, axis=0)  # (k,)
        medoids = xp[medoid_idx]
        new_cent = jnp.where((counts > 0)[:, None], medoids, cent)
        shift = jnp.sum((new_cent - cent) ** 2)
        return new_cent, shift, labels

    return step


class KMedoids(_KCluster):
    """K-Medoids with manhattan assignment (reference ``kmedoids.py:10``)."""

    def __init__(
        self,
        n_clusters: int = 8,
        init: Union[str, DNDarray] = "random",
        max_iter: int = 300,
        random_state: Optional[int] = None,
    ):
        from ..spatial.distance import manhattan

        super().__init__(
            metric=lambda x, y: manhattan(x, y),
            n_clusters=n_clusters,
            init=init,
            max_iter=max_iter,
            tol=-1.0,
            random_state=random_state,
        )

    def _converged(self, shift_sq: float) -> bool:
        """Medoid iteration converges at an exact fixpoint (centroids
        snap to data points, so the shift is exactly zero there)."""
        return shift_sq == 0.0

    def _step_dispatcher(self, phys_shape, n: int, comm):
        """Distributed per-iteration step — tape-compiled donated program
        under ``fusion.fit_enabled()``, legacy program otherwise."""
        k = self.n_clusters
        if not fusion.fit_enabled():
            return _kmedoids_step_fn(phys_shape, k, n, comm)
        eager = _kmedoids_eager_step(k, n)

        def step(xp, cent):
            return fusion.fit_step_call(
                ("kmedoids.step", tuple(phys_shape), k, n, comm.cache_key),
                lambda qk, ck, hk: _kmedoids_step_fn(
                    phys_shape, k, n, comm, fused=(qk, ck, hk)),
                (xp, cent), eager)

        return step

    def _local_step(self, logical, centroids):
        """Replicated-data step for the shared Lloyd driver: the eager
        step with an all-true row mask (ONE copy of the medoid-update
        mathematics to keep in sync)."""
        return _kmedoids_eager_step(
            self.n_clusters, logical.shape[0])(logical, centroids)

    def fit(self, x: DNDarray) -> "KMedoids":
        """Medoid iteration through the shared ``_run_lloyd`` driver
        (the historic batched/non-batched loop pair deduped into
        ``_KCluster``)."""
        if not isinstance(x, DNDarray):
            raise TypeError(f"input needs to be a DNDarray, but was {type(x)}")
        if x.split not in (None, 0):
            x = x.resplit(0)
        self._initialize_cluster_centers(x)

        n = x.shape[0]
        # fresh buffer: the fused step donates the carried centroids
        centroids = jnp.array(self._cluster_centers._logical(), jnp.float32)

        if x.split == 0 and x.comm.size > 1 and n > 0:
            xp = x.larray.astype(jnp.float32)
            step = self._step_dispatcher(xp.shape, n, x.comm)
            centroids, labels, it = self._run_lloyd(step, xp, centroids)
            self._cluster_centers = DNDarray.from_logical(
                centroids, None, x.device, x.comm)
            labels = jax.device_put(labels, x.comm.sharding(1, 0))
            self._labels = DNDarray(
                labels, (n,), types.canonical_heat_type(labels.dtype), 0,
                x.device, x.comm)
            self._n_iter = it
            return self

        logical = x._logical().astype(jnp.float32)
        centroids, labels, it = self._run_lloyd(
            self._local_step, logical, centroids)

        self._cluster_centers = DNDarray.from_logical(centroids, None, x.device, x.comm)
        self._labels = DNDarray.from_logical(
            labels, 0 if x.split == 0 else None, x.device, x.comm
        )
        self._n_iter = it
        return self
