"""KMedoids clustering (reference ``heat/cluster/kmedoids.py``).

Manhattan assignment; the centroid update snaps each cluster mean to the
nearest member point (the reference's medoid step). Fully distributed: one
jitted shard_map program per iteration — assignment and per-cluster
mean are local + psum, and the medoid snap is a value-index pmin tournament
(ties break to the lowest global row) whose winning row is broadcast with a
masked psum, the same pivot-row pattern as the distributed Gauss-Jordan.
The data is never gathered.
"""

from __future__ import annotations

from typing import Optional, Union

import jax
import jax.numpy as jnp
import numpy as np
from ..core._compat import shard_map

from ..core.dndarray import DNDarray
from ..core import types
from ..core._sort import _index_dtype
from ._kcluster import _KCluster

__all__ = ["KMedoids"]

_STEP_CACHE: dict = {}


def _kmedoids_step_fn(phys_shape, k: int, n: int, comm):
    """Jitted ``(x_phys, centroids) -> (new_centroids, shift, labels_phys)``."""
    key = ("kmedo", tuple(phys_shape), k, n, comm.cache_key)
    fn = _STEP_CACHE.get(key)
    if fn is not None:
        return fn
    p = comm.size
    c = phys_shape[0] // p
    idt = _index_dtype()

    def body(xb, cent):
        me = jax.lax.axis_index(comm.axis_name)
        gpos = me * c + jnp.arange(c, dtype=idt)
        valid = gpos < n
        dist = jnp.sum(jnp.abs(xb[:, None, :] - cent[None, :, :]), axis=-1)
        labels = jnp.argmin(dist, axis=1)
        member = (labels[:, None] == jnp.arange(k)[None, :]) & valid[:, None]
        counts = jax.lax.psum(jnp.sum(member.astype(idt), axis=0),
                              comm.axis_name)
        sums = jax.lax.psum(member.astype(xb.dtype).T @ xb, comm.axis_name)
        means = sums / jnp.maximum(counts, 1).astype(xb.dtype)[:, None]
        # snap to the nearest member point: per-cluster (distance, row) pmin
        d_mean = jnp.sum(jnp.abs(xb[:, None, :] - means[None, :, :]), axis=-1)
        d_mean = jnp.where(member, d_mean, jnp.inf)  # (c, k)
        loc_i = jnp.argmin(d_mean, axis=0)  # (k,)
        loc_v = jnp.take_along_axis(d_mean, loc_i[None, :], axis=0)[0]
        loc_g = gpos[loc_i]
        gmin = jax.lax.pmin(loc_v, comm.axis_name)
        big = jnp.iinfo(idt).max
        g_win = jax.lax.pmin(
            jnp.where(loc_v == gmin, loc_g, jnp.asarray(big, idt)),
            comm.axis_name)  # (k,) lowest global row among ties
        winner = gpos[:, None] == g_win[None, :]  # (c, k)
        medoids = jax.lax.psum(
            jnp.einsum("ck,cd->kd", winner.astype(xb.dtype), xb),
            comm.axis_name)
        new_cent = jnp.where((counts > 0)[:, None], medoids, cent)
        shift = jnp.sum((new_cent - cent) ** 2)
        return new_cent, shift, labels

    fn = jax.jit(
        shard_map(
            body, mesh=comm.mesh,
            in_specs=(comm.spec(2, 0), comm.spec(2, None)),
            out_specs=(comm.spec(2, None), comm.spec(0, None),
                       comm.spec(1, 0)),
            check_vma=False)
    )
    _STEP_CACHE[key] = fn
    return fn


class KMedoids(_KCluster):
    """K-Medoids with manhattan assignment (reference ``kmedoids.py:10``)."""

    def __init__(
        self,
        n_clusters: int = 8,
        init: Union[str, DNDarray] = "random",
        max_iter: int = 300,
        random_state: Optional[int] = None,
    ):
        from ..spatial.distance import manhattan

        super().__init__(
            metric=lambda x, y: manhattan(x, y),
            n_clusters=n_clusters,
            init=init,
            max_iter=max_iter,
            tol=-1.0,
            random_state=random_state,
        )

    def fit(self, x: DNDarray) -> "KMedoids":
        if not isinstance(x, DNDarray):
            raise TypeError(f"input needs to be a DNDarray, but was {type(x)}")
        if x.split not in (None, 0):
            x = x.resplit(0)
        self._initialize_cluster_centers(x)

        k = self.n_clusters
        xp = x.larray.astype(jnp.float32)
        centroids = self._cluster_centers._logical().astype(jnp.float32)
        n = x.shape[0]

        if x.split == 0 and x.comm.size > 1 and n > 0:
            step = _kmedoids_step_fn(xp.shape, k, n, x.comm)
            it = 0
            labels = None
            for it in range(1, self.max_iter + 1):
                centroids, shift, labels = step(xp, centroids)
                if float(shift) == 0.0:
                    break
            self._cluster_centers = DNDarray.from_logical(
                centroids, None, x.device, x.comm)
            self._labels = DNDarray(
                labels, (n,), types.canonical_heat_type(labels.dtype), 0,
                x.device, x.comm)
            self._n_iter = it
            return self

        logical = x._logical().astype(jnp.float32)
        it = 0
        for it in range(1, self.max_iter + 1):
            d = jnp.sum(jnp.abs(logical[:, None, :] - centroids[None, :, :]), axis=-1)
            labels = jnp.argmin(d, axis=1)
            member = labels[:, None] == jnp.arange(k)[None, :]
            counts = jnp.sum(member, axis=0)
            sums = member.astype(logical.dtype).T @ logical
            means = sums / jnp.maximum(counts, 1)[:, None]
            # snap each mean to the nearest member point (the medoid step)
            d_mean = jnp.sum(jnp.abs(logical[:, None, :] - means[None, :, :]), axis=-1)
            d_mean = jnp.where(member, d_mean, jnp.inf)
            medoid_idx = jnp.argmin(d_mean, axis=0)  # (k,)
            new_centroids = logical[medoid_idx]
            new_centroids = jnp.where((counts > 0)[:, None], new_centroids, centroids)
            shift = float(jnp.sum((new_centroids - centroids) ** 2))
            centroids = new_centroids
            if shift == 0.0:
                break

        self._cluster_centers = DNDarray.from_logical(centroids, None, x.device, x.comm)
        self._labels = DNDarray.from_logical(
            labels, 0 if x.split == 0 else None, x.device, x.comm
        )
        self._n_iter = it
        return self
