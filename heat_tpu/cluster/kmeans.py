"""KMeans clustering (reference ``heat/cluster/kmeans.py``).

The benchmark workload (SURVEY.md §3.4, §6). The reference's Lloyd epoch is a
chain of cdist → argmin → k masked sum/count Allreduces
(``kmeans.py:73-139``). Here one **fused jitted Lloyd step** runs per
iteration, with two backends:

* **Pallas (TPU)**: :func:`heat_tpu.core.pallas_kernels.kmeans_step_tile`
  streams each device's X shard from HBM exactly ONCE per iteration — the
  assignment GEMM, argmin, one-hot update GEMM and inertia terms all
  consume the same VMEM-resident tile — wrapped in ``shard_map`` with a
  ``psum`` for the cross-device centroid reduction.
* **XLA (fallback)**: squared-distance GEMM tile (MXU) → argmin → one-hot
  matmul for the centroid sums → GSPMD ``psum``.

Labels are not materialized in the hot loop (an N-vector write per
iteration); ``fit`` computes them once after convergence.
"""

from __future__ import annotations

from typing import Optional, Union

import numpy as np

import jax
import jax.numpy as jnp
from ..core._compat import shard_map
from jax.sharding import PartitionSpec as P

from ..core.dndarray import DNDarray
from ..core import fusion, types
from ..core.pallas_kernels import (kmeans_step_tile, kmeans_pallas_enabled,
                                   _kmeans_sums_mode, _kmeans_block_rows)
from ._kcluster import _KCluster

__all__ = ["KMeans"]

# cache of jitted Lloyd steps keyed by (physical shape, dtype, k, comm, path)
_STEP_CACHE: dict = {}


_acc_dtype = types.accumulation_dtype


def _finish_update(sums, counts, centroids):
    """Centroid division + empty-cluster keep + shift (replicated inputs).
    Runs in the accumulation dtype; the returned centroids match the
    carried-in centroid dtype so iteration carries stay dtype-stable."""
    acc = sums.dtype
    cacc = centroids.astype(acc)
    new_centroids = sums / jnp.maximum(counts, 1.0)[:, None]
    new_centroids = jnp.where((counts > 0)[:, None], new_centroids, cacc)
    shift = jnp.sum((new_centroids - cacc) ** 2)
    return new_centroids.astype(centroids.dtype), shift


def _lloyd_partial(xp, centroids, valid, k, jdt, acc):
    """Masked per-shard Lloyd partials ``(sums, counts, inertia)`` —
    squared-distance GEMM tile → argmin → one-hot GEMM. ``valid`` is the
    ``(rows, 1)`` bool row mask (canonical padding / chunk tail); the
    same function serves the global GSPMD body, the shard_map block body
    and the streaming partial program."""
    xf = xp.astype(acc)
    x2 = jnp.sum(xf * xf, axis=1, keepdims=True)
    cacc = centroids.astype(acc)
    c2 = jnp.sum(cacc * cacc, axis=1, keepdims=True).T
    xc = jax.lax.dot_general(
        xp, centroids.astype(jdt),
        dimension_numbers=(((1,), (1,)), ((), ())),
        preferred_element_type=acc)
    d2 = x2 + c2 - 2.0 * xc  # (rows, k) distances in acc
    labels = jnp.argmin(d2, axis=1)
    onehot = (labels[:, None] == jnp.arange(k)[None, :]) & valid
    counts = jnp.sum(onehot.astype(acc), axis=0)  # (k,)
    sums = jax.lax.dot_general(  # (k, d) GEMM
        onehot.astype(jdt), xp,
        dimension_numbers=(((0,), (0,)), ((), ())),
        preferred_element_type=acc)
    inertia = jnp.sum(jnp.where(valid[:, 0], jnp.min(d2, axis=1),
                                jnp.zeros((), acc)))
    return sums, counts, inertia


def _make_step_body(phys_shape, jdt, k, n_valid, comm, sums_mode,
                    block_rows=None):
    """(xp, centroids) -> (new_centroids, inertia, shift); one Lloyd step.

    ``sums_mode`` is resolved by the CALLER and passed down explicitly so the
    step cache key and the traced kernel can never disagree (resolving the
    env var again at trace time could bake a different mode into an entry
    keyed under the lookup-time mode)."""
    if sums_mode:
        chunk = phys_shape[0] // comm.size
        axis = comm.axis_name

        def device_step(xp_blk, centroids):
            rank = jax.lax.axis_index(axis)
            row = rank * chunk + jax.lax.broadcasted_iota(
                jnp.int32, (chunk, 1), 0)
            mask = (row < n_valid).astype(xp_blk.dtype)
            sums, counts, inertia = kmeans_step_tile(
                xp_blk, centroids, mask, block_rows=block_rows,
                sums_mode=sums_mode)
            sums = jax.lax.psum(sums, axis)
            counts = jax.lax.psum(counts, axis)
            inertia = jax.lax.psum(inertia, axis)
            new_centroids, shift = _finish_update(sums, counts, centroids)
            return new_centroids, inertia, shift

        return shard_map(
            device_step, mesh=comm.mesh,
            in_specs=(comm.spec(2, 0), P()),
            out_specs=(P(), P(), P()),
            check_vma=False)

    acc = _acc_dtype(jdt)

    def _step(xp, centroids):
        # valid-row mask for canonical padding; elementwise consumers
        # cast in-register (HBM reads stay bf16 for half-precision
        # storage); GEMMs take the narrow inputs at MXU rate and
        # accumulate in ``acc`` via preferred_element_type — the psums
        # are GSPMD-placed on this path
        row = jax.lax.broadcasted_iota(jnp.int32, (phys_shape[0], 1), 0)
        sums, counts, inertia = _lloyd_partial(
            xp, centroids, row < n_valid, k, jdt, acc)
        new_centroids, shift = _finish_update(sums, counts, centroids)
        return new_centroids, inertia, shift

    return _step


def _use_pallas_step(jdt) -> bool:
    """The fused kernel returns sums/counts/inertia in the INPUT dtype
    (``pallas_kernels._kmeans_step_tile``); half-precision inputs would
    round cluster counts >256 before the psum, so they stay on the XLA
    mixed-precision path (bf16 reads, f32 accumulation)."""
    return (kmeans_pallas_enabled()
            and _acc_dtype(jdt) == jnp.dtype(jdt))


def _lloyd_step_fn(phys_shape, jdt, k, n_valid, comm):
    sums_mode = _use_pallas_step(jdt) and _kmeans_sums_mode()
    block_rows = _kmeans_block_rows() if sums_mode else None
    key = (phys_shape, str(jdt), k, n_valid, comm.cache_key, sums_mode,
           block_rows)
    fn = _STEP_CACHE.get(key)
    if fn is None:
        fn = jax.jit(_make_step_body(phys_shape, jdt, k, n_valid, comm,
                                     sums_mode, block_rows))
        _STEP_CACHE[key] = fn
    return fn


def _lloyd_fused_fn(phys_shape, jdt, k, n_valid, comm, qk, ck, hk):
    """The tape-compiled Lloyd step for split-0 data: ONE donated
    shard_map executable per iteration — distance GEMM tile → argmin →
    masked one-hot sums/counts → convergence shift on shard-local
    blocks, with the centroid sums, counts AND inertia PACKED into a
    single flattened all-reduce (``fusion.packed_psum``; the captured
    quant/chunk/hier tuples are pinned so the traced wire format always
    matches the program key). The carried centroids are DONATED — XLA
    updates the replicated (k, d) buffer in place across iterations.
    Returns ``(new_centroids, shift, inertia)``."""
    sums_mode = _use_pallas_step(jdt) and _kmeans_sums_mode()
    block_rows = _kmeans_block_rows() if sums_mode else None
    key = ("fused", phys_shape, str(jdt), k, n_valid, comm.cache_key,
           sums_mode, block_rows, qk, ck, hk)
    fn = _STEP_CACHE.get(key)
    if fn is not None:
        return fn
    acc = _acc_dtype(jdt)
    chunk = phys_shape[0] // comm.size
    axis = comm.axis_name

    def device_step(xp_blk, centroids):
        rank = jax.lax.axis_index(axis)
        row = rank * chunk + jax.lax.broadcasted_iota(
            jnp.int32, (chunk, 1), 0)
        if sums_mode:
            mask = (row < n_valid).astype(xp_blk.dtype)
            sums, counts, inertia = kmeans_step_tile(
                xp_blk, centroids, mask, block_rows=block_rows,
                sums_mode=sums_mode)
        else:
            sums, counts, inertia = _lloyd_partial(
                xp_blk, centroids, row < n_valid, k, jdt, acc)
        sums, counts, inertia = fusion.packed_psum(
            [sums, counts, inertia], (axis,), quant=qk, chunks=ck,
            hier=hk)
        new_centroids, shift = _finish_update(sums, counts, centroids)
        return new_centroids, shift, inertia

    fn = jax.jit(
        shard_map(device_step, mesh=comm.mesh,
                  in_specs=(comm.spec(2, 0), P()),
                  out_specs=(P(), P(), P()), check_vma=False),
        donate_argnums=(1,))
    _STEP_CACHE[key] = fn
    return fn


def _lloyd_fused_gspmd_fn(phys_shape, jdt, k, n_valid, comm, qk, ck, hk):
    """The tape-compiled Lloyd step for replicated (split=None) data:
    the GSPMD body compiled as one donated executable — replicated data
    places zero collectives, so there is nothing to pack; the codec
    tuples still key the program for uniformity."""
    key = ("fusedg", phys_shape, str(jdt), k, n_valid, comm.cache_key,
           qk, ck, hk)
    fn = _STEP_CACHE.get(key)
    if fn is not None:
        return fn
    single = _make_step_body(phys_shape, jdt, k, n_valid, comm, False)

    def step(xp, centroids):
        new_centroids, inertia, shift = single(xp, centroids)
        return new_centroids, shift, inertia

    fn = jax.jit(step, donate_argnums=(1,))
    _STEP_CACHE[key] = fn
    return fn


def _lloyd_eager_step(phys_shape, jdt, k, n_valid):
    """The SAME Lloyd mathematics dispatched op-by-op (unjitted jnp with
    GSPMD-placed collectives): the ``fit.step.dispatch`` degrade path
    and the analytics bench's eager leg. Returns the fit-step tuple
    ``(new_centroids, shift, inertia)``."""
    acc = _acc_dtype(jdt)

    def step(xp, centroids):
        row = jax.lax.broadcasted_iota(jnp.int32, (phys_shape[0], 1), 0)
        sums, counts, inertia = _lloyd_partial(
            xp, centroids, row < n_valid, k, jdt, acc)
        new_centroids, shift = _finish_update(sums, counts, centroids)
        return new_centroids, shift, inertia

    return step


def _stream_partial_fn(phys_shape, jdt, k, comm, split, qk, ck, hk):
    """The out-of-core epoch's per-chunk program: masked Lloyd partials
    over one chunk, the (sums, counts, inertia) family packed into one
    all-reduce, ADDED into donated device accumulators —
    ``(xp, centroids, n_valid, s_acc, c_acc, i_acc) -> updated accs``.
    ``n_valid`` is a TRACED scalar so the tail chunk shares the full
    chunks' program; the accumulators are donated so an epoch is one
    dispatch per chunk with zero host round-trips."""
    key = ("spart", phys_shape, str(jdt), k, comm.cache_key, split,
           qk, ck, hk)
    fn = _STEP_CACHE.get(key)
    if fn is not None:
        return fn
    acc = _acc_dtype(jdt)
    if split == 0:
        chunk = phys_shape[0] // comm.size
        axis = comm.axis_name

        def pbody(xp_blk, centroids, n_valid, s_acc, c_acc, i_acc):
            rank = jax.lax.axis_index(axis)
            row = rank * chunk + jax.lax.broadcasted_iota(
                jnp.int32, (chunk, 1), 0)
            sums, counts, inertia = _lloyd_partial(
                xp_blk, centroids, row < n_valid, k, jdt, acc)
            sums, counts, inertia = fusion.packed_psum(
                [sums, counts, inertia], (axis,), quant=qk, chunks=ck,
                hier=hk)
            return s_acc + sums, c_acc + counts, i_acc + inertia

        fn = jax.jit(
            shard_map(pbody, mesh=comm.mesh,
                      in_specs=(comm.spec(2, 0), P(), P(), P(), P(), P()),
                      out_specs=(P(), P(), P()), check_vma=False),
            donate_argnums=(3, 4, 5))
    else:
        fn = jax.jit(_stream_partial_eager(phys_shape, jdt, k),
                     donate_argnums=(3, 4, 5))
    _STEP_CACHE[key] = fn
    return fn


def _stream_partial_eager(phys_shape, jdt, k):
    """GSPMD/global form of the streaming partial — unjitted it is the
    chunk program's eager degrade path."""
    acc = _acc_dtype(jdt)

    def pbody(xp, centroids, n_valid, s_acc, c_acc, i_acc):
        row = jax.lax.broadcasted_iota(jnp.int32, (phys_shape[0], 1), 0)
        sums, counts, inertia = _lloyd_partial(
            xp, centroids, row < n_valid, k, jdt, acc)
        return s_acc + sums, c_acc + counts, i_acc + inertia

    return pbody


def _stream_partial_legacy_fn(phys_shape, jdt, k):
    """The ``HEAT_TPU_FUSION_FIT=0`` streaming partial: the GSPMD body
    jitted plain — XLA-placed separate collectives, NO packed_psum, NO
    donation, no fusion keying — honoring the escape hatch's documented
    contract on the out-of-core path too."""
    key = ("spart-legacy", phys_shape, str(jdt), k)
    fn = _STEP_CACHE.get(key)
    if fn is None:
        fn = jax.jit(_stream_partial_eager(phys_shape, jdt, k))
        _STEP_CACHE[key] = fn
    return fn


def _assign_fn(phys_shape, jdt, k, n_valid, comm):
    """Final assignment pass: labels AND inertia against the same (final)
    centroids, so ``labels_``/``cluster_centers_``/``inertia_`` are mutually
    consistent (sklearn convention). The x^2 term does not change the
    argmin; it is added back only for the inertia."""
    key = ("assign", phys_shape, str(jdt), k, n_valid, comm.cache_key)
    fn = _STEP_CACHE.get(key)
    if fn is None:

        acc = _acc_dtype(jdt)

        def _assign(xp, centroids):
            row = jax.lax.broadcasted_iota(jnp.int32, (phys_shape[0],), 0)
            valid = row < n_valid
            cacc = centroids.astype(acc)
            c2 = jnp.sum(cacc * cacc, axis=1)[None, :]
            xc = jax.lax.dot_general(
                xp, centroids.astype(jdt),
                dimension_numbers=(((1,), (1,)), ((), ())),
                preferred_element_type=acc)
            scores = c2 - 2.0 * xc
            labels = jnp.argmin(scores, axis=1)
            xf = xp.astype(acc)
            x2 = jnp.sum(xf * xf, axis=1)
            inertia = jnp.sum(
                jnp.where(valid, x2 + jnp.min(scores, axis=1),
                          jnp.zeros((), acc)))
            return labels, inertia

        fn = jax.jit(_assign)
        _STEP_CACHE[key] = fn
    return fn


def _lloyd_fori_fn(phys_shape, jdt, k, n_valid, comm):
    """Lloyd iterations with a *runtime* trip count (``lax.fori_loop``).

    The whole hot loop is one XLA program compiled once and reused for any
    iteration count (the compiled-epoch discipline SURVEY.md §7 calls for,
    hard part 5). Used by the benchmark driver, which times two different
    trip counts with the same executable and differences them to cancel
    constant dispatch/transfer overhead."""
    sums_mode = _use_pallas_step(jdt) and _kmeans_sums_mode()
    block_rows = _kmeans_block_rows() if sums_mode else None
    key = ("fori", phys_shape, str(jdt), k, n_valid, comm.cache_key,
           sums_mode, block_rows)
    fn = _STEP_CACHE.get(key)
    if fn is None:
        if sums_mode:
            # shard_map OUTSIDE the loop: the valid mask is computed once
            # and the whole iteration sequence is one per-device program
            chunk = phys_shape[0] // comm.size
            axis = comm.axis_name

            def _run_device(xp_blk, centroids, iters):
                rank = jax.lax.axis_index(axis)
                row = rank * chunk + jax.lax.broadcasted_iota(
                    jnp.int32, (chunk, 1), 0)
                mask = (row < n_valid).astype(xp_blk.dtype)

                def body(_, carry):
                    c, _, _ = carry
                    sums, counts, inertia = kmeans_step_tile(
                        xp_blk, c, mask, block_rows=block_rows,
                        sums_mode=sums_mode)
                    sums = jax.lax.psum(sums, axis)
                    counts = jax.lax.psum(counts, axis)
                    inertia = jax.lax.psum(inertia, axis)
                    new_c, shift = _finish_update(sums, counts, c)
                    return new_c, inertia, shift

                z = jnp.zeros((), jdt)
                return jax.lax.fori_loop(0, iters, body, (centroids, z, z))

            fn = jax.jit(shard_map(
                _run_device, mesh=comm.mesh,
                in_specs=(comm.spec(2, 0), P(), P()),
                out_specs=(P(), P(), P()),
                check_vma=False))
        else:
            single = _make_step_body(phys_shape, jdt, k, n_valid, comm,
                                     sums_mode)

            def _run(xp, centroids, iters):
                def body(_, carry):
                    c, _, _ = carry
                    return single(xp, c)

                z = jnp.zeros((), _acc_dtype(jdt))
                c, inertia, shift = jax.lax.fori_loop(
                    0, iters, body, (centroids, z, z))
                return c, inertia, shift

            fn = jax.jit(_run)
        _STEP_CACHE[key] = fn
    return fn


class KMeans(_KCluster):
    """K-Means with Lloyd's algorithm (reference ``kmeans.py:12``).

    Parameters match the reference: ``n_clusters``, ``init`` ("random",
    "kmeans++", or a (k, d) DNDarray), ``max_iter``, ``tol``, ``random_state``.
    """

    def __init__(
        self,
        n_clusters: int = 8,
        init: Union[str, DNDarray] = "random",
        max_iter: int = 300,
        tol: float = 1e-4,
        random_state: Optional[int] = None,
    ):
        from ..spatial.distance import cdist

        super().__init__(
            metric=lambda x, y: cdist(x, y, quadratic_expansion=True),
            n_clusters=n_clusters,
            init=init,
            max_iter=max_iter,
            tol=tol,
            random_state=random_state,
        )

    def _lloyd_dispatcher(self, phys_shape, jdt, n, comm, split):
        """The per-iteration step callable ``(xp, centroids) ->
        (new_centroids, shift, inertia)``. Under ``fusion.fit_enabled()``
        it is a ``fusion.fit_step_call`` dispatch of the donated,
        packed-collective executable (key lookup + one dispatch per
        Lloyd iteration, ``fit.step.dispatch`` degrading to the eager
        op-by-op iteration); with the engine off it is the legacy
        GSPMD step program, bitwise today's behavior."""
        k = self.n_clusters
        if not fusion.fit_enabled():
            legacy = _lloyd_step_fn(phys_shape, jdt, k, n, comm)

            def legacy_step(xp, centroids):
                new_centroids, inertia, shift = legacy(xp, centroids)
                return new_centroids, shift, inertia

            return legacy_step
        sums_mode = _use_pallas_step(jdt) and _kmeans_sums_mode()
        block_rows = _kmeans_block_rows() if sums_mode else None
        builder = _lloyd_fused_fn if split == 0 else _lloyd_fused_gspmd_fn
        eager = _lloyd_eager_step(phys_shape, jdt, k, n)

        def step(xp, centroids):
            return fusion.fit_step_call(
                ("kmeans.lloyd", phys_shape, str(jdt), k, n,
                 comm.cache_key, split, sums_mode, block_rows),
                lambda qk, ck, hk: builder(
                    phys_shape, jdt, k, n, comm, qk, ck, hk),
                (xp, centroids), eager)

        return step

    def fit(self, x: DNDarray) -> "KMeans":
        """Lloyd iteration to convergence (reference ``kmeans.py:102-139``):
        the shared ``_run_lloyd`` driver dispatching ONE compiled step per
        iteration. The per-iteration ``float(shift)`` read doubles as the
        program serialization sync (see ``_run_lloyd``), including when
        ``tol < 0`` disables the convergence break (the benchmarks'
        run-all-iterations mode)."""
        if not isinstance(x, DNDarray):
            raise TypeError(f"input needs to be a DNDarray, but was {type(x)}")
        if x.ndim != 2:
            raise ValueError("input needs to be 2-dimensional (n_samples, n_features)")
        if x.split not in (None, 0):
            x = x.resplit(0)

        self._initialize_cluster_centers(x)
        jdt = x.dtype.jax_type()
        if types.heat_type_is_exact(x.dtype):
            jdt = jnp.dtype(jnp.float32)
        xp = x.larray.astype(jdt)
        n = x.shape[0]
        # fresh buffer: the fused step DONATES the carried centroids, and
        # the seed array may alias self._cluster_centers' storage
        centroids = jnp.array(self._cluster_centers._logical(), jdt)
        step = self._lloyd_dispatcher(xp.shape, jdt, n, x.comm, x.split)
        centroids, _, it = self._run_lloyd(step, xp, centroids)

        self._cluster_centers = DNDarray.from_logical(centroids, None, x.device, x.comm)
        labels, inertia = _assign_fn(
            xp.shape, jdt, self.n_clusters, n, x.comm)(xp, centroids)
        self._labels = DNDarray(
            labels, (n,), types.canonical_heat_type(labels.dtype), 0 if x.split == 0 else None,
            x.device, x.comm,
        )
        self._inertia = float(inertia)
        self._n_iter = it
        return self

    # ------------------------------------------------------------------ #
    # out-of-core streaming fit: the EXACT epoch form                    #
    # ------------------------------------------------------------------ #
    def _stream_dtype(self, chunk: DNDarray):
        jdt = chunk.dtype.jax_type()
        if types.heat_type_is_exact(chunk.dtype):
            jdt = jnp.dtype(jnp.float32)
        return jnp.dtype(jdt)

    def _stream_accumulate(self, chunks, centroids, meta):
        """One full pass over the stream: the additive (sums, counts,
        inertia) family accumulates chunk-by-chunk into donated device
        buffers — one compiled dispatch per chunk, zero host round-trips
        inside the pass (``HEAT_TPU_FUSION_FIT=0`` runs the plain-jit
        legacy partial: separate collectives, no donation)."""
        k = self.n_clusters
        jdt = meta["jdt"]
        acc = _acc_dtype(jdt)
        comm = meta["comm"]
        sums = jnp.zeros((k, meta["d"]), acc)
        counts = jnp.zeros((k,), acc)
        inertia = jnp.zeros((), acc)
        for chunk in chunks():
            xp = chunk.larray.astype(jdt)
            split = 0 if chunk.split == 0 else None
            nvalid = jnp.asarray(chunk.shape[0], jnp.int32)
            args = (xp, centroids, nvalid, sums, counts, inertia)
            if fusion.fit_enabled():
                sums, counts, inertia = fusion.fit_step_call(
                    ("kmeans.stream", xp.shape, str(jdt), k,
                     comm.cache_key, split),
                    lambda qk, ck, hk, _s=xp.shape, _sp=split:
                        _stream_partial_fn(_s, jdt, k, comm, _sp,
                                           qk, ck, hk),
                    args, _stream_partial_eager(xp.shape, jdt, k))
            else:
                sums, counts, inertia = _stream_partial_legacy_fn(
                    xp.shape, jdt, k)(*args)
        return sums, counts, inertia

    def _stream_epoch(self, chunks, centroids, meta):
        """One EXACT full-batch Lloyd epoch out-of-core: the centroids
        update ONCE per epoch from the accumulated pass, so the streamed
        fit is value-equal to the in-memory fit up to float summation
        reassociation (``doc/analytics.md`` numerics contract)."""
        sums, counts, _ = self._stream_accumulate(chunks, centroids, meta)
        return _finish_update(sums, counts, centroids)

    def _stream_finalize(self, chunks, centroids, meta):
        """One extra accumulation pass against the FINAL centroids so
        ``inertia_`` means the same thing as after ``fit()`` (whose
        final assignment pass scores the final centroids) — without it
        the streamed figure would be one Lloyd update stale."""
        _, _, inertia = self._stream_accumulate(chunks, centroids, meta)
        self._inertia = float(inertia)
