"""Shared k-clustering machinery (reference ``heat/cluster/_kcluster.py``).

The reference's per-centroid ``Bcast`` initialization (``_kcluster.py:87-194``)
and cdist+argmin assignment (``:196``) become, respectively, gathers of k
sampled rows (k tiny) and one fused GEMM-tile + argmin program per shard.

The Lloyd driver lives HERE, once: :meth:`_KCluster._run_lloyd` is the one
``for it in range(1, self.max_iter + 1)`` loop every estimator's ``fit``
(and every ``fit_stream`` epoch) runs, so the tape-compiled fit step —
``fusion.fit_step_call`` dispatching ONE donated packed-collective
executable per iteration — lands in one place instead of the historic
copy-pasted batched/non-batched loop pairs (``kmedians.py:130/:144``,
``kmedoids.py:120/:134``). :meth:`fit_stream` is the out-of-core entry
point: a re-iterable chunk source (``io.DataStream`` or any chunk
iterable) is consumed epoch-by-epoch, chunk-by-chunk, so datasets larger
than host RAM train without ever materializing.
"""

from __future__ import annotations

from typing import Callable, Optional, Tuple

import numpy as np

import jax
import jax.numpy as jnp

from ..core import factories, fusion, random as ht_random, types
from ..core.base import BaseEstimator, ClusteringMixin
from ..core.dndarray import DNDarray

__all__ = ["_KCluster"]


def _chunk_source(stream, rows_per_chunk):
    """Normalize a ``fit_stream`` source into ``(factory, shape_hint)``:
    ``factory()`` yields a fresh pass of split-0 DNDarray chunks each
    epoch. Accepts an ``io.DataStream`` (re-opened per pass), a zero-arg
    callable returning an iterable, or a concrete chunk sequence."""
    if hasattr(stream, "iter_chunks"):
        if rows_per_chunk is None:
            raise ValueError(
                "rows_per_chunk is required when streaming from a "
                "DataStream source")
        return (lambda: stream.iter_chunks(rows_per_chunk),
                tuple(getattr(stream, "shape", ()) or ()) or None)
    if callable(stream):
        return stream, None
    seq = list(stream)
    if not seq:
        raise ValueError("fit_stream needs at least one chunk")
    return (lambda: iter(seq)), None


class _KCluster(ClusteringMixin, BaseEstimator):
    """Base class for KMeans/KMedians/KMedoids (reference ``_kcluster.py:16``)."""

    def __init__(self, metric: Callable, n_clusters: int, init, max_iter: int, tol: float, random_state):
        import numbers

        if (
            isinstance(n_clusters, bool)
            or not isinstance(n_clusters, numbers.Integral)
            or n_clusters < 1
        ):
            raise ValueError(f"n_clusters must be a positive int, got {n_clusters!r}")
        self.n_clusters = int(n_clusters)
        self.init = init
        self.max_iter = max_iter
        self.tol = tol
        self.random_state = random_state

        self._cluster_centers = None
        self._labels = None
        self._inertia = None
        self._n_iter = None
        self._metric = metric

    @property
    def cluster_centers_(self) -> DNDarray:
        return self._cluster_centers

    @property
    def labels_(self) -> DNDarray:
        return self._labels

    @property
    def inertia_(self) -> float:
        return self._inertia

    @property
    def n_iter_(self) -> int:
        return self._n_iter

    # ------------------------------------------------------------------ #
    # the ONE Lloyd driver (tape-compiled fit steps land here)           #
    # ------------------------------------------------------------------ #
    def _converged(self, shift_sq: float) -> bool:
        """Convergence predicate on the squared centroid shift; KMedoids
        overrides with its exact-fixpoint test."""
        return self.tol >= 0 and shift_sq <= self.tol * self.tol

    def _run_lloyd(self, step, xp, centroids):
        """The shared ``for it in range(1, self.max_iter + 1)`` loop.

        ``step(xp, centroids) -> (new_centroids, shift, aux)`` — under
        ``fusion.fit_enabled()`` one compiled donated executable per
        iteration (key lookup + one dispatch); the ``float(shift)`` read
        is the per-iteration host sync (it also serializes back-to-back
        collective programs, the PR-2-era CPU rendezvous discipline).
        Returns ``(centroids, aux, n_iter)``.
        """
        it = 0
        aux = None
        for it in range(1, self.max_iter + 1):
            centroids, shift, aux = step(xp, centroids)
            if self._converged(float(shift)):
                break
        return centroids, aux, it

    # ------------------------------------------------------------------ #
    # out-of-core streaming fit                                          #
    # ------------------------------------------------------------------ #
    def _stream_chunk_update(self, chunk: DNDarray, centroids):
        """One minibatch update from one chunk (the default
        ``_stream_epoch`` hook): one distributed fit step for split-0
        multi-device chunks, the replicated local step otherwise.
        Serves any subclass that defines ``_step_dispatcher`` /
        ``_local_step`` (KMedians, KMedoids); KMeans overrides the whole
        epoch with the exact accumulation form instead."""
        if not hasattr(self, "_step_dispatcher"):
            raise NotImplementedError(
                f"{type(self).__name__} does not implement streamed fitting")
        n = chunk.shape[0]
        if chunk.split == 0 and chunk.comm.size > 1 and n > 0:
            xp = chunk.larray.astype(jnp.float32)
            centroids, _, _ = self._step_dispatcher(
                xp.shape, n, chunk.comm)(xp, centroids)
            return centroids
        logical = chunk._logical().astype(jnp.float32)
        centroids, _, _ = self._local_step(logical, centroids)
        return centroids

    def _stream_epoch(self, chunks, centroids, meta):
        """One pass over all chunks. Default: MINIBATCH semantics — the
        centroids are updated after every chunk with that chunk's own
        update (approximate; the per-chunk update has no memory of the
        other chunks). Returns ``(new_centroids, epoch_shift_sq)``."""
        # copy: the first chunk's fused step DONATES the carried buffer,
        # and the epoch shift still needs the starting values
        start = jnp.array(centroids)
        for chunk in chunks():
            centroids = self._stream_chunk_update(chunk, centroids)
        shift = jnp.sum((centroids - start) ** 2)
        return centroids, shift

    def _stream_dtype(self, chunk: DNDarray):
        return jnp.dtype(jnp.float32)

    def _init_stream_centers(self, chunks, shape_hint):
        """Streamed centroid seeding, value-equal to the in-memory
        ``_initialize_cluster_centers`` for the supported inits:

        * an explicit ``(k, d)`` DNDarray — used as-is (replicated);
        * ``"random"`` — the SAME ``ht_random.randint`` draw as the
          in-memory path (same seed → same global row indices), with the
          sampled rows collected during one metadata pass over the
          chunks, so streamed and in-memory fits see identical seeds;
        * ``"kmeans++"`` — rejected: D²-weighted seeding needs one full
          distance pass over the data per seed and is not available
          out-of-core.

        Returns ``(centroids, meta)`` where ``meta`` carries the stream
        geometry (n rows, feature count, comm/device, dtype).
        """
        k = self.n_clusters
        if self.random_state is not None:
            ht_random.seed(self.random_state)
        if isinstance(self.init, str) and self.init in (
                "kmeans++", "probability_based"):
            raise ValueError(
                "fit_stream supports init='random' or explicit centroids; "
                "kmeans++ seeding needs full-data distance passes")
        meta = {"n": 0, "d": None, "comm": None, "device": None}
        want = None
        rows: dict = {}
        if isinstance(self.init, str) and self.init == "random":
            # shape hint (DataStream) lets the draw happen before the
            # pass; otherwise a first metadata pass counts rows
            if shape_hint is not None:
                meta["n"] = int(shape_hint[0])
            else:
                for chunk in chunks():
                    meta["n"] += chunk.shape[0]
        lo = 0
        for chunk in chunks():
            if meta["d"] is None:
                if chunk.ndim != 2:
                    raise ValueError(
                        "fit_stream chunks must be 2-D (rows, features)")
                meta["d"] = chunk.shape[1]
                meta["comm"] = chunk.comm
                meta["device"] = chunk.device
                meta["jdt"] = self._stream_dtype(chunk)
                if isinstance(self.init, str) and self.init == "random":
                    if shape_hint is None and meta["n"] <= 0:
                        raise ValueError("fit_stream saw zero rows")
                    idx = ht_random.randint(
                        0, meta["n"], (k,), split=None, comm=chunk.comm)
                    want = np.asarray(idx.larray)
            hi = lo + chunk.shape[0]
            if want is not None:
                sel = [(j, int(g) - lo) for j, g in enumerate(want)
                       if lo <= int(g) < hi]
                if sel:
                    got = chunk[np.asarray([r for _, r in sel])] \
                        .resplit(None)._logical()
                    for (j, _), row in zip(sel, got):
                        rows[j] = row
                if len(rows) == len(want):
                    # every drawn seed row collected — don't pay the
                    # rest of the disk pass for nothing
                    lo = hi
                    break
            else:
                # explicit init: only the stream geometry was needed —
                # don't pay a full disk pass for it
                lo = hi
                break
            lo = hi
        if shape_hint is not None:
            meta["n"] = int(shape_hint[0])
        else:
            meta["n"] = max(meta["n"], lo)
        if meta["d"] is None:
            raise ValueError("fit_stream needs at least one chunk")
        if isinstance(self.init, DNDarray):
            if self.init.shape != (k, meta["d"]):
                raise ValueError(
                    f"passed centroids must have shape ({k}, {meta['d']}),"
                    f" got {self.init.shape}")
            centers = self.init.resplit(None)._logical()
        elif want is not None:
            missing = [int(want[j]) for j in range(k) if j not in rows]
            if missing:
                raise ValueError(
                    f"fit_stream random init: drawn seed rows {missing} "
                    f"were never produced by the stream (stream shorter "
                    f"than its declared {meta['n']} rows?)")
            centers = jnp.stack([rows[j] for j in range(k)])
        else:
            raise ValueError(
                f"initialization method {self.init!r} is not supported "
                "for fit_stream")
        return jnp.array(centers, meta["jdt"]), meta

    def fit_stream(self, stream, rows_per_chunk: Optional[int] = None):
        """Out-of-core fit from a re-iterable chunk source.

        ``stream`` is an ``io.DataStream`` (``ht.load_hdf5(...,
        stream=True)``) — each epoch calls
        ``stream.iter_chunks(rows_per_chunk)`` and the data re-streams
        from disk, so the peak resident footprint is ONE chunk, never
        the dataset — or a zero-arg callable returning a fresh chunk
        iterable, or a concrete sequence of split-0 DNDarray chunks.

        KMeans runs the EXACT epoch form (per-chunk partial sums/counts
        accumulated into donated device buffers, centroids updated once
        per epoch — value-equal to the in-memory fit up to float
        summation reassociation, ``doc/analytics.md``); KMedians and
        KMedoids run the documented minibatch form (per-chunk updates,
        approximate). ``labels_`` is not materialized (an n-vector for
        an out-of-core n — use ``predict`` chunk-wise); ``n_iter_`` and
        ``cluster_centers_`` are set as in ``fit``.
        """
        chunks, shape_hint = _chunk_source(stream, rows_per_chunk)
        centroids, meta = self._init_stream_centers(chunks, shape_hint)
        it = 0
        for it in range(1, self.max_iter + 1):
            centroids, shift = self._stream_epoch(chunks, centroids, meta)
            if self._converged(float(shift)):
                break
        self._stream_finalize(chunks, centroids, meta)
        self._cluster_centers = DNDarray.from_logical(
            centroids, None, meta["device"], meta["comm"])
        self._labels = None
        self._n_iter = it
        return self

    def _stream_finalize(self, chunks, centroids, meta):
        """Post-loop hook with the FINAL centroids. Default no-op;
        KMeans spends one extra pass here to measure ``inertia_``
        against the final centroids — the same semantics as ``fit()``'s
        final assignment pass."""

    # ------------------------------------------------------------------ #
    def _initialize_cluster_centers(self, x: DNDarray):
        """Centroid init (reference ``_kcluster.py:87-194``)."""
        k = self.n_clusters
        if self.random_state is not None:
            ht_random.seed(self.random_state)
        if isinstance(self.init, DNDarray):
            if self.init.shape != (k, x.shape[1]):
                raise ValueError(
                    f"passed centroids must have shape ({k}, {x.shape[1]}), got {self.init.shape}"
                )
            self._cluster_centers = self.init.resplit(None)
            return
        if self.init == "random":
            idx = ht_random.randint(0, x.shape[0], (k,), split=None, comm=x.comm)
            # ring-gather the k sampled rows (the reference Bcasts each
            # sampled row, ``_kcluster.py:87-194``) — no materialization
            rows = x[np.asarray(idx.larray)].resplit(None)
            self._cluster_centers = rows
            return
        if self.init in ("kmeans++", "probability_based"):
            self._cluster_centers = self._kmeanspp(x)
            # synchronize before the caller launches its iteration programs:
            # concurrently-executing collective programs can interleave
            # their rendezvous on the CPU backend and deadlock (observed
            # with the seeding cdist ring vs the first Lloyd step)
            jax.block_until_ready(self._cluster_centers.larray)
            return
        raise ValueError(f"initialization method {self.init!r} is not supported")

    def _kmeanspp(self, x: DNDarray) -> DNDarray:
        """k-means++ D²-weighted seeding (reference ``_kcluster.py:120-194``).

        The heavy part (min squared distance per point) runs sharded on
        device; the D²-weighted draw itself is O(n) on k tiny vectors and
        runs on HOST with concrete indices. Device-side cumsum/searchsorted/
        gather-by-traced-index would each be a separate tiny collective
        program — a stampede of in-process rendezvous that can starve the
        host thread pool and hard-abort XLA's CPU runtime (observed on
        single-core CI hosts with an 8-device mesh).
        """
        n = x.shape[0]
        k = self.n_clusters
        first = int(ht_random.randint(0, n, (1,), comm=x.comm)._logical()[0])

        def row(i):  # one sampled row, ring-gathered — never the array
            return x[np.asarray([i])].resplit(None)._logical()

        centers = row(first)
        for _ in range(1, k):
            d2 = np.asarray(self._pairwise_sq_dist_to(x, centers))  # (n,), host
            u = float(ht_random.rand(1, comm=x.comm)._logical()[0])
            total = max(float(d2.sum()), 1e-30)
            cdf = np.cumsum(d2 / total)
            nxt = min(int(np.searchsorted(cdf, u)), n - 1)
            centers = jnp.concatenate([centers, row(nxt)], axis=0)
        return DNDarray.from_logical(centers, None, x.device, x.comm)

    def _pairwise_sq_dist_to(self, x: DNDarray, centers) -> jnp.ndarray:
        """Min squared distance of every point to the current center set."""
        from ..spatial.distance import cdist

        c = DNDarray.from_logical(centers, None, x.device, x.comm)
        d = cdist(x, c, quadratic_expansion=True)
        # replicate before the caller's host-side draw: a split array's
        # shards span non-addressable devices on multi-host pods, where a
        # host fetch of the sharded value would raise
        dmin = d.min(axis=1).resplit(None)
        return dmin._logical() ** 2

    def _assign_to_cluster(self, x: DNDarray) -> DNDarray:
        """Nearest-centroid assignment (reference ``_kcluster.py:196``)."""
        d = self._metric(x, self._cluster_centers)
        return d.argmin(axis=1)

    def predict(self, x: DNDarray) -> DNDarray:
        """Nearest learned centroid for each sample (reference ``_kcluster.py:230``)."""
        if not isinstance(x, DNDarray):
            raise TypeError(f"input needs to be a DNDarray, but was {type(x)}")
        return self._assign_to_cluster(x)
