"""Shared k-clustering machinery (reference ``heat/cluster/_kcluster.py``).

The reference's per-centroid ``Bcast`` initialization (``_kcluster.py:87-194``)
and cdist+argmin assignment (``:196``) become, respectively, gathers of k
sampled rows (k tiny) and one fused GEMM-tile + argmin program per shard.
"""

from __future__ import annotations

from typing import Callable, Optional, Tuple

import numpy as np

import jax
import jax.numpy as jnp

from ..core import factories, random as ht_random, types
from ..core.base import BaseEstimator, ClusteringMixin
from ..core.dndarray import DNDarray

__all__ = ["_KCluster"]


class _KCluster(ClusteringMixin, BaseEstimator):
    """Base class for KMeans/KMedians/KMedoids (reference ``_kcluster.py:16``)."""

    def __init__(self, metric: Callable, n_clusters: int, init, max_iter: int, tol: float, random_state):
        import numbers

        if (
            isinstance(n_clusters, bool)
            or not isinstance(n_clusters, numbers.Integral)
            or n_clusters < 1
        ):
            raise ValueError(f"n_clusters must be a positive int, got {n_clusters!r}")
        self.n_clusters = int(n_clusters)
        self.init = init
        self.max_iter = max_iter
        self.tol = tol
        self.random_state = random_state

        self._cluster_centers = None
        self._labels = None
        self._inertia = None
        self._n_iter = None
        self._metric = metric

    @property
    def cluster_centers_(self) -> DNDarray:
        return self._cluster_centers

    @property
    def labels_(self) -> DNDarray:
        return self._labels

    @property
    def inertia_(self) -> float:
        return self._inertia

    @property
    def n_iter_(self) -> int:
        return self._n_iter

    # ------------------------------------------------------------------ #
    def _initialize_cluster_centers(self, x: DNDarray):
        """Centroid init (reference ``_kcluster.py:87-194``)."""
        k = self.n_clusters
        if self.random_state is not None:
            ht_random.seed(self.random_state)
        if isinstance(self.init, DNDarray):
            if self.init.shape != (k, x.shape[1]):
                raise ValueError(
                    f"passed centroids must have shape ({k}, {x.shape[1]}), got {self.init.shape}"
                )
            self._cluster_centers = self.init.resplit(None)
            return
        if self.init == "random":
            idx = ht_random.randint(0, x.shape[0], (k,), split=None, comm=x.comm)
            # ring-gather the k sampled rows (the reference Bcasts each
            # sampled row, ``_kcluster.py:87-194``) — no materialization
            rows = x[np.asarray(idx.larray)].resplit(None)
            self._cluster_centers = rows
            return
        if self.init in ("kmeans++", "probability_based"):
            self._cluster_centers = self._kmeanspp(x)
            # synchronize before the caller launches its iteration programs:
            # concurrently-executing collective programs can interleave
            # their rendezvous on the CPU backend and deadlock (observed
            # with the seeding cdist ring vs the first Lloyd step)
            jax.block_until_ready(self._cluster_centers.larray)
            return
        raise ValueError(f"initialization method {self.init!r} is not supported")

    def _kmeanspp(self, x: DNDarray) -> DNDarray:
        """k-means++ D²-weighted seeding (reference ``_kcluster.py:120-194``).

        The heavy part (min squared distance per point) runs sharded on
        device; the D²-weighted draw itself is O(n) on k tiny vectors and
        runs on HOST with concrete indices. Device-side cumsum/searchsorted/
        gather-by-traced-index would each be a separate tiny collective
        program — a stampede of in-process rendezvous that can starve the
        host thread pool and hard-abort XLA's CPU runtime (observed on
        single-core CI hosts with an 8-device mesh).
        """
        n = x.shape[0]
        k = self.n_clusters
        first = int(ht_random.randint(0, n, (1,), comm=x.comm)._logical()[0])

        def row(i):  # one sampled row, ring-gathered — never the array
            return x[np.asarray([i])].resplit(None)._logical()

        centers = row(first)
        for _ in range(1, k):
            d2 = np.asarray(self._pairwise_sq_dist_to(x, centers))  # (n,), host
            u = float(ht_random.rand(1, comm=x.comm)._logical()[0])
            total = max(float(d2.sum()), 1e-30)
            cdf = np.cumsum(d2 / total)
            nxt = min(int(np.searchsorted(cdf, u)), n - 1)
            centers = jnp.concatenate([centers, row(nxt)], axis=0)
        return DNDarray.from_logical(centers, None, x.device, x.comm)

    def _pairwise_sq_dist_to(self, x: DNDarray, centers) -> jnp.ndarray:
        """Min squared distance of every point to the current center set."""
        from ..spatial.distance import cdist

        c = DNDarray.from_logical(centers, None, x.device, x.comm)
        d = cdist(x, c, quadratic_expansion=True)
        # replicate before the caller's host-side draw: a split array's
        # shards span non-addressable devices on multi-host pods, where a
        # host fetch of the sharded value would raise
        dmin = d.min(axis=1).resplit(None)
        return dmin._logical() ** 2

    def _assign_to_cluster(self, x: DNDarray) -> DNDarray:
        """Nearest-centroid assignment (reference ``_kcluster.py:196``)."""
        d = self._metric(x, self._cluster_centers)
        return d.argmin(axis=1)

    def predict(self, x: DNDarray) -> DNDarray:
        """Nearest learned centroid for each sample (reference ``_kcluster.py:230``)."""
        if not isinstance(x, DNDarray):
            raise TypeError(f"input needs to be a DNDarray, but was {type(x)}")
        return self._assign_to_cluster(x)
