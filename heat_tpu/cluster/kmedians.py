"""KMedians clustering (reference ``heat/cluster/kmedians.py``).

Same Lloyd skeleton as KMeans but the centroid update is the per-cluster
coordinate-wise **median**; implemented as a masked ``nanmedian`` over the
gathered per-cluster columns (order statistics are data-dependent; k and d
are small, n is sharded for the assignment step).
"""

from __future__ import annotations

from typing import Optional, Union

import jax.numpy as jnp
import numpy as np

from ..core.dndarray import DNDarray
from ..core import types
from ._kcluster import _KCluster

__all__ = ["KMedians"]


class KMedians(_KCluster):
    """K-Medians with manhattan assignment (reference ``kmedians.py:10``)."""

    def __init__(
        self,
        n_clusters: int = 8,
        init: Union[str, DNDarray] = "random",
        max_iter: int = 300,
        tol: float = 1e-4,
        random_state: Optional[int] = None,
    ):
        from ..spatial.distance import manhattan

        super().__init__(
            metric=lambda x, y: manhattan(x, y),
            n_clusters=n_clusters,
            init=init,
            max_iter=max_iter,
            tol=tol,
            random_state=random_state,
        )

    def fit(self, x: DNDarray) -> "KMedians":
        if not isinstance(x, DNDarray):
            raise TypeError(f"input needs to be a DNDarray, but was {type(x)}")
        if x.split not in (None, 0):
            x = x.resplit(0)
        self._initialize_cluster_centers(x)

        k = self.n_clusters
        logical = x._logical().astype(jnp.float32)
        centroids = self._cluster_centers._logical().astype(jnp.float32)

        it = 0
        for it in range(1, self.max_iter + 1):
            labels = self._assign_labels(logical, centroids)
            new_centroids = self._median_update(logical, labels, centroids, k)
            shift = float(jnp.sum((new_centroids - centroids) ** 2))
            centroids = new_centroids
            if self.tol >= 0 and shift <= self.tol * self.tol:
                break

        self._cluster_centers = DNDarray.from_logical(centroids, None, x.device, x.comm)
        self._labels = DNDarray.from_logical(
            labels, 0 if x.split == 0 else None, x.device, x.comm
        )
        self._n_iter = it
        return self

    @staticmethod
    def _assign_labels(logical, centroids):
        d = jnp.sum(jnp.abs(logical[:, None, :] - centroids[None, :, :]), axis=-1)
        return jnp.argmin(d, axis=1)

    @staticmethod
    def _median_update(logical, labels, centroids, k):
        member = labels[:, None] == jnp.arange(k)[None, :]  # (n, k)
        vals = jnp.where(member[:, :, None], logical[:, None, :], jnp.nan)
        med = jnp.nanmedian(vals, axis=0)  # (k, d)
        return jnp.where(jnp.isnan(med), centroids, med)
