"""KMedians clustering (reference ``heat/cluster/kmedians.py``).

Same Lloyd skeleton as KMeans but the centroid update is the per-cluster
coordinate-wise **median**. Fully distributed: one jitted shard_map program
per iteration runs the manhattan assignment shard-locally, then ONE batched
merge-split network sort over all (cluster, feature) columns at once
(non-members and padding carry +inf keys, so each column's valid order
statistics occupy its leading global positions — k-fold fewer collective
rounds than per-cluster sorts, at k x block memory) and selects the median
ranks with two masked psums — the data is never gathered (the reference
runs ``ht.percentile`` per cluster over the split array the same way).
"""

from __future__ import annotations

from typing import Optional, Union

import jax
import jax.numpy as jnp
import numpy as np
from ..core._compat import shard_map

from ..core.dndarray import DNDarray
from ..core import types
from ..core._sort import (_float_sort_key, _index_dtype, _network_sort,
                          _role_tables, batcher_rounds)
from ._kcluster import _KCluster

__all__ = ["KMedians"]

_STEP_CACHE: dict = {}


def _kmedians_step_fn(phys_shape, k: int, n: int, comm):
    """Jitted ``(x_phys, centroids) -> (new_centroids, shift, labels_phys)``:
    one full Lloyd/median iteration over the mesh."""
    key = ("kmed", tuple(phys_shape), k, n, comm.cache_key)
    fn = _STEP_CACHE.get(key)
    if fn is not None:
        return fn
    p = comm.size
    c = phys_shape[0] // p
    d = phys_shape[1]
    rounds = batcher_rounds(p)
    roles = _role_tables(rounds, p)
    idt = _index_dtype()
    kdt = jnp.int32  # float32 sort keys
    pad_key = jnp.iinfo(kdt).max

    def body(xb, cent):
        me = jax.lax.axis_index(comm.axis_name)
        gpos = me * c + jnp.arange(c, dtype=idt)
        valid = gpos < n
        dist = jnp.sum(jnp.abs(xb[:, None, :] - cent[None, :, :]), axis=-1)
        labels = jnp.argmin(dist, axis=1)
        member = (labels[:, None] == jnp.arange(k)[None, :]) & valid[:, None]
        counts = jax.lax.psum(jnp.sum(member.astype(idt), axis=0),
                              comm.axis_name)  # (k,)
        # ONE batched network sort over all (cluster, feature) columns —
        # same total traffic as k separate sorts, k-fold fewer rounds
        mask = member.T[:, None, :]  # (k, 1, c)
        vals = jnp.broadcast_to(xb.T[None, :, :], (k, d, c))
        keys = jnp.where(mask, _float_sort_key(vals), pad_key)
        _, (sv,) = _network_sort(keys, (vals,), rounds, roles, c, False,
                                 comm.axis_name)  # (k, d, c)
        lo = jnp.maximum(counts - 1, 0) // 2  # (k,)
        hi = counts // 2
        sel = gpos[None, None, :]
        vlo = jax.lax.psum(
            jnp.sum(jnp.where(sel == lo[:, None, None], sv, 0), axis=-1),
            comm.axis_name)  # (k, d)
        vhi = jax.lax.psum(
            jnp.sum(jnp.where(sel == hi[:, None, None], sv, 0), axis=-1),
            comm.axis_name)
        med = 0.5 * (vlo + vhi)
        new_cent = jnp.where((counts > 0)[:, None], med, cent)
        shift = jnp.sum((new_cent - cent) ** 2)
        return new_cent, shift, labels

    spec_x = comm.spec(2, 0)
    fn = jax.jit(
        shard_map(
            body, mesh=comm.mesh, in_specs=(spec_x, comm.spec(2, None)),
            out_specs=(comm.spec(2, None), comm.spec(0, None),
                       comm.spec(1, 0)),
            check_vma=False)
    )
    _STEP_CACHE[key] = fn
    return fn


class KMedians(_KCluster):
    """K-Medians with manhattan assignment (reference ``kmedians.py:10``)."""

    def __init__(
        self,
        n_clusters: int = 8,
        init: Union[str, DNDarray] = "random",
        max_iter: int = 300,
        tol: float = 1e-4,
        random_state: Optional[int] = None,
    ):
        from ..spatial.distance import manhattan

        super().__init__(
            metric=lambda x, y: manhattan(x, y),
            n_clusters=n_clusters,
            init=init,
            max_iter=max_iter,
            tol=tol,
            random_state=random_state,
        )

    def fit(self, x: DNDarray) -> "KMedians":
        if not isinstance(x, DNDarray):
            raise TypeError(f"input needs to be a DNDarray, but was {type(x)}")
        if x.split not in (None, 0):
            x = x.resplit(0)
        self._initialize_cluster_centers(x)

        k = self.n_clusters
        xp = x.larray.astype(jnp.float32)
        centroids = self._cluster_centers._logical().astype(jnp.float32)
        n = x.shape[0]

        if x.split == 0 and x.comm.size > 1 and n > 0:
            step = _kmedians_step_fn(xp.shape, k, n, x.comm)
            it = 0
            labels = None
            for it in range(1, self.max_iter + 1):
                centroids, shift, labels = step(xp, centroids)
                if self.tol >= 0 and float(shift) <= self.tol * self.tol:
                    break
            self._cluster_centers = DNDarray.from_logical(
                centroids, None, x.device, x.comm)
            self._labels = DNDarray(
                labels, (n,), types.canonical_heat_type(labels.dtype), 0,
                x.device, x.comm)
            self._n_iter = it
            return self

        logical = x._logical().astype(jnp.float32)
        it = 0
        for it in range(1, self.max_iter + 1):
            labels = self._assign_labels(logical, centroids)
            new_centroids = self._median_update(logical, labels, centroids, k)
            shift = float(jnp.sum((new_centroids - centroids) ** 2))
            centroids = new_centroids
            if self.tol >= 0 and shift <= self.tol * self.tol:
                break

        self._cluster_centers = DNDarray.from_logical(centroids, None, x.device, x.comm)
        self._labels = DNDarray.from_logical(
            labels, 0 if x.split == 0 else None, x.device, x.comm
        )
        self._n_iter = it
        return self

    @staticmethod
    def _assign_labels(logical, centroids):
        d = jnp.sum(jnp.abs(logical[:, None, :] - centroids[None, :, :]), axis=-1)
        return jnp.argmin(d, axis=1)

    @staticmethod
    def _median_update(logical, labels, centroids, k):
        member = labels[:, None] == jnp.arange(k)[None, :]  # (n, k)
        vals = jnp.where(member[:, :, None], logical[:, None, :], jnp.nan)
        med = jnp.nanmedian(vals, axis=0)  # (k, d)
        return jnp.where(jnp.isnan(med), centroids, med)
