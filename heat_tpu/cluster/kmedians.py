"""KMedians clustering (reference ``heat/cluster/kmedians.py``).

Same Lloyd skeleton as KMeans but the centroid update is the per-cluster
coordinate-wise **median**. Fully distributed: one jitted shard_map program
per iteration runs the manhattan assignment shard-locally, then ONE batched
merge-split network sort over all (cluster, feature) columns at once
(non-members and padding carry +inf keys, so each column's valid order
statistics occupy its leading global positions — k-fold fewer collective
rounds than per-cluster sorts, at k x block memory) and selects the median
ranks with two masked psums — the data is never gathered (the reference
runs ``ht.percentile`` per cluster over the split array the same way).
"""

from __future__ import annotations

from typing import Optional, Union

import jax
import jax.numpy as jnp
import numpy as np
from ..core._compat import shard_map

from ..core.dndarray import DNDarray
from ..core import fusion, types
from ..core._sort import (_float_sort_key, _index_dtype, _network_sort,
                          _role_tables, batcher_rounds)
from ._kcluster import _KCluster

__all__ = ["KMedians"]

_STEP_CACHE: dict = {}


def _kmedians_step_fn(phys_shape, k: int, n: int, comm, fused=None):
    """Jitted ``(x_phys, centroids) -> (new_centroids, shift, labels_phys)``:
    one full Lloyd/median iteration over the mesh.

    ``fused=None`` is the legacy program (today's dispatch, bitwise);
    ``fused=(quant_key, chunk_key, hier_key)`` builds the tape-compiled
    sibling: the two median-rank selection psums PACK into one flattened
    all-reduce (pinned to the captured codec tuples) and the carried
    centroids are DONATED."""
    key = ("kmed", tuple(phys_shape), k, n, comm.cache_key, fused)
    fn = _STEP_CACHE.get(key)
    if fn is not None:
        return fn
    p = comm.size
    c = phys_shape[0] // p
    d = phys_shape[1]
    rounds = batcher_rounds(p)
    roles = _role_tables(rounds, p)
    idt = _index_dtype()
    kdt = jnp.int32  # float32 sort keys
    pad_key = jnp.iinfo(kdt).max

    def body(xb, cent):
        me = jax.lax.axis_index(comm.axis_name)
        gpos = me * c + jnp.arange(c, dtype=idt)
        valid = gpos < n
        dist = jnp.sum(jnp.abs(xb[:, None, :] - cent[None, :, :]), axis=-1)
        labels = jnp.argmin(dist, axis=1)
        member = (labels[:, None] == jnp.arange(k)[None, :]) & valid[:, None]
        counts = jax.lax.psum(jnp.sum(member.astype(idt), axis=0),
                              comm.axis_name)  # (k,)
        # ONE batched network sort over all (cluster, feature) columns —
        # same total traffic as k separate sorts, k-fold fewer rounds
        mask = member.T[:, None, :]  # (k, 1, c)
        vals = jnp.broadcast_to(xb.T[None, :, :], (k, d, c))
        keys = jnp.where(mask, _float_sort_key(vals), pad_key)
        _, (sv,) = _network_sort(keys, (vals,), rounds, roles, c, False,
                                 comm.axis_name)  # (k, d, c)
        lo = jnp.maximum(counts - 1, 0) // 2  # (k,)
        hi = counts // 2
        sel = gpos[None, None, :]
        plo = jnp.sum(jnp.where(sel == lo[:, None, None], sv, 0), axis=-1)
        phi = jnp.sum(jnp.where(sel == hi[:, None, None], sv, 0), axis=-1)
        if fused is None:
            vlo = jax.lax.psum(plo, comm.axis_name)  # (k, d)
            vhi = jax.lax.psum(phi, comm.axis_name)
        else:
            qk, ck, hk = fused
            vlo, vhi = fusion.packed_psum(
                [plo, phi], (comm.axis_name,), quant=qk, chunks=ck,
                hier=hk)
        med = 0.5 * (vlo + vhi)
        new_cent = jnp.where((counts > 0)[:, None], med, cent)
        shift = jnp.sum((new_cent - cent) ** 2)
        return new_cent, shift, labels

    spec_x = comm.spec(2, 0)
    fn = jax.jit(
        shard_map(
            body, mesh=comm.mesh, in_specs=(spec_x, comm.spec(2, None)),
            out_specs=(comm.spec(2, None), comm.spec(0, None),
                       comm.spec(1, 0)),
            check_vma=False),
        donate_argnums=(1,) if fused is not None else ())
    _STEP_CACHE[key] = fn
    return fn


def _kmedians_eager_step(k: int, n: int):
    """The same manhattan-assignment/median mathematics dispatched
    op-by-op (unjitted jnp, GSPMD collectives): the ``fit.step.dispatch``
    degrade path. The median comes from ``nanmedian`` over non-members
    masked to NaN — the average of the same two central order statistics
    the sort-network program selects."""

    def step(xp, cent):
        gpos = jnp.arange(xp.shape[0])
        valid = gpos < n
        dist = jnp.sum(jnp.abs(xp[:, None, :] - cent[None, :, :]), axis=-1)
        labels = jnp.argmin(dist, axis=1)
        member = (labels[:, None] == jnp.arange(k)[None, :]) & valid[:, None]
        counts = jnp.sum(member, axis=0)
        vals = jnp.where(member[:, :, None], xp[:, None, :], jnp.nan)
        med = jnp.nanmedian(vals, axis=0)  # (k, d)
        new_cent = jnp.where((counts > 0)[:, None], med, cent)
        shift = jnp.sum((new_cent - cent) ** 2)
        return new_cent, shift, labels

    return step


class KMedians(_KCluster):
    """K-Medians with manhattan assignment (reference ``kmedians.py:10``)."""

    def __init__(
        self,
        n_clusters: int = 8,
        init: Union[str, DNDarray] = "random",
        max_iter: int = 300,
        tol: float = 1e-4,
        random_state: Optional[int] = None,
    ):
        from ..spatial.distance import manhattan

        super().__init__(
            metric=lambda x, y: manhattan(x, y),
            n_clusters=n_clusters,
            init=init,
            max_iter=max_iter,
            tol=tol,
            random_state=random_state,
        )

    def _step_dispatcher(self, phys_shape, n: int, comm):
        """The distributed per-iteration step ``(xp, centroids) ->
        (new_centroids, shift, labels_phys)`` — the tape-compiled donated
        program under ``fusion.fit_enabled()`` (with the eager op-by-op
        degrade path), the legacy program otherwise."""
        k = self.n_clusters
        if not fusion.fit_enabled():
            return _kmedians_step_fn(phys_shape, k, n, comm)
        eager = _kmedians_eager_step(k, n)

        def step(xp, cent):
            return fusion.fit_step_call(
                ("kmedians.step", tuple(phys_shape), k, n, comm.cache_key),
                lambda qk, ck, hk: _kmedians_step_fn(
                    phys_shape, k, n, comm, fused=(qk, ck, hk)),
                (xp, cent), eager)

        return step

    def _local_step(self, logical, centroids):
        """Replicated-data step for the shared Lloyd driver."""
        labels = self._assign_labels(logical, centroids)
        new_centroids = self._median_update(
            logical, labels, centroids, self.n_clusters)
        shift = jnp.sum((new_centroids - centroids) ** 2)
        return new_centroids, shift, labels

    def fit(self, x: DNDarray) -> "KMedians":
        """Lloyd/median iteration through the shared ``_run_lloyd``
        driver (the historic batched/non-batched loop pair deduped into
        ``_KCluster``)."""
        if not isinstance(x, DNDarray):
            raise TypeError(f"input needs to be a DNDarray, but was {type(x)}")
        if x.split not in (None, 0):
            x = x.resplit(0)
        self._initialize_cluster_centers(x)

        k = self.n_clusters
        n = x.shape[0]
        # fresh buffer: the fused step donates the carried centroids
        centroids = jnp.array(self._cluster_centers._logical(), jnp.float32)

        if x.split == 0 and x.comm.size > 1 and n > 0:
            xp = x.larray.astype(jnp.float32)
            step = self._step_dispatcher(xp.shape, n, x.comm)
            centroids, labels, it = self._run_lloyd(step, xp, centroids)
            self._cluster_centers = DNDarray.from_logical(
                centroids, None, x.device, x.comm)
            # an eager-degraded final iteration may hand back labels in
            # a different layout — pin the split-0 sharding the wrapper
            # below claims
            labels = jax.device_put(labels, x.comm.sharding(1, 0))
            self._labels = DNDarray(
                labels, (n,), types.canonical_heat_type(labels.dtype), 0,
                x.device, x.comm)
            self._n_iter = it
            return self

        logical = x._logical().astype(jnp.float32)
        centroids, labels, it = self._run_lloyd(
            self._local_step, logical, centroids)

        self._cluster_centers = DNDarray.from_logical(centroids, None, x.device, x.comm)
        self._labels = DNDarray.from_logical(
            labels, 0 if x.split == 0 else None, x.device, x.comm
        )
        self._n_iter = it
        return self

    @staticmethod
    def _assign_labels(logical, centroids):
        d = jnp.sum(jnp.abs(logical[:, None, :] - centroids[None, :, :]), axis=-1)
        return jnp.argmin(d, axis=1)

    @staticmethod
    def _median_update(logical, labels, centroids, k):
        member = labels[:, None] == jnp.arange(k)[None, :]  # (n, k)
        vals = jnp.where(member[:, :, None], logical[:, None, :], jnp.nan)
        med = jnp.nanmedian(vals, axis=0)  # (k, d)
        return jnp.where(jnp.isnan(med), centroids, med)
