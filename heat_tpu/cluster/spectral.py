"""Spectral clustering (reference ``heat/cluster/spectral.py``).

Pipeline parity with the reference (``spectral.py:12,150``): rbf kernel →
``Laplacian.construct`` → Lanczos tridiagonalization (distributed matvecs) →
dense eig of the small tridiagonal T → KMeans on the leading eigenvectors.

Both hot loops of this pipeline ride the tape-compiled fit-step engine
(``fusion.fit_step_call``, ``doc/analytics.md``): the Lanczos inner loop
dispatches ONE donated executable per iteration (``linalg.solver.lanczos``)
and the KMeans assignment runs the packed-collective Lloyd step — escape
hatch ``HEAT_TPU_FUSION_FIT=0`` restores the legacy per-op/legacy-program
paths end to end.
"""

from __future__ import annotations

from typing import Optional, Union

import jax.numpy as jnp
import numpy as np

from ..core import types
from ..core.base import BaseEstimator, ClusteringMixin
from ..core.dndarray import DNDarray
from ..core.linalg import lanczos, matmul
from ..graph.laplacian import Laplacian
from .kmeans import KMeans

__all__ = ["Spectral"]


class Spectral(ClusteringMixin, BaseEstimator):
    """Spectral clustering on the graph Laplacian (reference ``spectral.py:12``)."""

    def __init__(
        self,
        n_clusters: Optional[int] = None,
        gamma: float = 1.0,
        metric: str = "rbf",
        laplacian: str = "fully_connected",
        threshold: float = 1.0,
        boundary: str = "upper",
        n_lanczos: int = 300,
        assign_labels: str = "kmeans",
        **params,
    ):
        self.n_clusters = n_clusters
        self.gamma = gamma
        self.metric = metric
        self.laplacian = laplacian
        self.threshold = threshold
        self.boundary = boundary
        self.n_lanczos = n_lanczos
        self.assign_labels = assign_labels

        from ..spatial import distance

        if metric == "rbf":
            sigma = float(np.sqrt(1.0 / (2.0 * gamma)))
            sim = lambda x: distance.rbf(x, sigma=sigma, quadratic_expansion=True)
        elif metric == "euclidean":
            sim = lambda x: distance.cdist(x, quadratic_expansion=True)
        else:
            raise NotImplementedError(f"metric {metric!r} is not supported")

        self._laplacian = Laplacian(
            sim,
            definition="norm_sym",
            mode=laplacian,
            threshold_key=boundary,
            threshold_value=threshold,
        )
        self._labels = None
        self._eigenvectors = None
        self._n_iter = None

    @property
    def labels_(self):
        return self._labels

    @property
    def n_iter_(self):
        """Lloyd iterations the embedding KMeans ran (None before fit)."""
        return self._n_iter

    def _spectral_embedding(self, x: DNDarray):
        """Laplacian eigenvector embedding via Lanczos (reference ``spectral.py:120-148``)."""
        L = self._laplacian.construct(x)
        n = L.shape[0]
        m = min(self.n_lanczos, n)
        V, T = lanczos(L, m)
        # dense eig of the small tridiagonal (reference uses torch.eig)
        evals, evecs = jnp.linalg.eigh(T._logical())
        # eigenvectors of L ≈ V @ evecs
        eigenvectors = matmul(V, DNDarray.from_logical(evecs, None, x.device, x.comm))
        return evals, eigenvectors

    def fit(self, x: DNDarray) -> "Spectral":
        """Embed and cluster (reference ``spectral.py:150``)."""
        if not isinstance(x, DNDarray):
            raise TypeError(f"input needs to be a DNDarray, but was {type(x)}")
        evals, evecs = self._spectral_embedding(x)

        if self.n_clusters is None:
            # eigengap heuristic (reference ``spectral.py:170``)
            gaps = jnp.diff(evals)
            self.n_clusters = int(jnp.argmax(gaps)) + 1
        k = int(self.n_clusters)

        # leading-k column slice of the (possibly split) eigenvector matrix:
        # columns are unsharded, so this is the basic shard-local getitem
        emb = evecs[:, :k]
        if self.assign_labels == "kmeans":
            kmeans = KMeans(n_clusters=k, init="kmeans++")
            kmeans.fit(emb)
            self._labels = kmeans.labels_
            self._eigenvectors = evecs
            self._n_iter = kmeans.n_iter_
        else:
            raise NotImplementedError(f"assign_labels={self.assign_labels!r} not supported")
        return self

    def predict(self, x: DNDarray) -> DNDarray:
        if self._labels is None:
            raise RuntimeError("fit needs to be called before predict")
        return self._labels
