"""Bundled demo datasets (synthetic stand-ins for the reference's
``heat/datasets/``; see ``_generate.py``)."""

import os


def path(name: str) -> str:
    """Absolute path of a bundled dataset file, e.g. ``path("iris.h5")``."""
    return os.path.join(os.path.dirname(os.path.abspath(__file__)), name)
