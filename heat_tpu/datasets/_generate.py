"""Regenerates the bundled demo datasets (synthetic, deterministic).

The reference ships small real datasets (``heat/datasets/``: iris.csv/h5,
diabetes.h5) for its examples and io/cluster/regression tests. We bundle
*synthetic* stand-ins with the same shapes and file layout — three labeled
Gaussian clusters in 4-D for ``iris`` (150x4, 3 classes of 50) and a sparse
linear-model regression set for ``diabetes`` (442x10 with targets) — so no
data files are copied from the reference.

Run ``python -m heat_tpu.datasets._generate`` to rebuild the files in place.
"""

import os

import numpy as np


def make_iris(rng: np.random.Generator) -> tuple:
    centers = np.array(
        [[5.0, 3.4, 1.5, 0.25], [5.9, 2.8, 4.3, 1.3], [6.6, 3.0, 5.6, 2.0]], np.float32
    )
    scales = np.array(
        [[0.35, 0.38, 0.17, 0.10], [0.52, 0.31, 0.47, 0.20], [0.64, 0.32, 0.55, 0.27]], np.float32
    )
    xs, ys = [], []
    for c in range(3):
        xs.append(rng.normal(centers[c], scales[c], size=(50, 4)).astype(np.float32))
        ys.append(np.full(50, c, np.int64))
    return np.concatenate(xs), np.concatenate(ys)


def make_diabetes(rng: np.random.Generator) -> tuple:
    n, d = 442, 10
    x = rng.normal(size=(n, d)).astype(np.float32)
    x /= np.sqrt((x**2).mean(0, keepdims=True))
    beta = np.array([0.0, -11.4, 25.7, 16.8, -44.6, 24.7, 7.8, 8.6, 35.1, 0.0], np.float32)
    y = x @ beta + rng.normal(scale=4.0, size=n).astype(np.float32) + 152.0
    return x, y.astype(np.float32)[:, None]


def main() -> None:
    import h5py

    here = os.path.dirname(os.path.abspath(__file__))
    rng = np.random.default_rng(20260729)

    x, y = make_iris(rng)
    with h5py.File(os.path.join(here, "iris.h5"), "w") as f:
        f.create_dataset("data", data=x)
    np.savetxt(os.path.join(here, "iris.csv"), x, delimiter=";", fmt="%.4f")
    np.savetxt(os.path.join(here, "iris_labels.csv"), y[:, None], delimiter=";", fmt="%d")
    # NetCDF copy (reference ships iris.nc) — written directly as classic
    # NetCDF-3 so every backend (netCDF4 or the scipy fallback) reads it
    from scipy.io import netcdf_file

    with netcdf_file(os.path.join(here, "iris.nc"), "w") as f:
        f.createDimension("dim_0", x.shape[0])
        f.createDimension("dim_1", x.shape[1])
        var = f.createVariable("data", x.dtype, ("dim_0", "dim_1"))
        var[:] = x

    xd, yd = make_diabetes(rng)
    with h5py.File(os.path.join(here, "diabetes.h5"), "w") as f:
        f.create_dataset("x", data=xd)
        f.create_dataset("y", data=yd)


if __name__ == "__main__":
    main()
