"""Optimizers (reference ``heat/optim/``): torch.optim-style constructors
mapped onto optax, plus the data-parallel wrappers and DASO."""

from .dp_optimizer import (
    DASO,
    Adadelta,
    Adagrad,
    Adam,
    AdamW,
    DataParallelOptimizer,
    RMSprop,
    SGD,
)
from . import utils
from .utils import DetectMetricPlateau
