"""Data-parallel optimizers (reference ``heat/optim/dp_optimizer.py``).

``DataParallelOptimizer`` (reference ``:834-877``) wraps a local optimizer
and defers ``step()`` into the fused train step. ``DASO`` (reference
``:46-833``) is the hierarchical **Distributed Asynchronous & Selective
Optimization** scheme: node-local sync every batch, global sync every
``global_skips`` batches with gradients downcast to bf16 for the wire
(the reference needs custom MPI reduce ops for that, ``:21-43`` — bf16 is a
native reduce dtype on TPU ICI). The TPU analogue keeps DASO's *schedule*
(skipped global syncs, bf16 wire format, plateau-driven phase changes) on a
two-level mesh: the fast axis is intra-node ICI, the slow axis is the
DCN/inter-node dimension.
"""

from __future__ import annotations

from typing import Callable, Optional

import numpy as np

import jax
import jax.numpy as jnp
import optax

from ..core.communication import sanitize_comm
from .utils import DetectMetricPlateau

__all__ = ["DataParallelOptimizer", "DASO", "SGD", "Adam", "AdamW", "Adagrad", "Adadelta", "RMSprop"]


def _make_tx(name: str, lr: float, **kwargs):
    table = {
        "sgd": lambda: optax.sgd(lr, momentum=kwargs.get("momentum", 0.0), nesterov=kwargs.get("nesterov", False)),
        "adam": lambda: optax.adam(lr, b1=kwargs.get("b1", 0.9), b2=kwargs.get("b2", 0.999)),
        "adamw": lambda: optax.adamw(lr, weight_decay=kwargs.get("weight_decay", 1e-4)),
        "adagrad": lambda: optax.adagrad(lr),
        "adadelta": lambda: optax.adadelta(lr),
        "rmsprop": lambda: optax.rmsprop(lr),
    }
    return table[name]()


def SGD(lr: float = 0.01, **kwargs):
    """torch.optim.SGD-style constructor → optax (reference optim passthrough,
    ``heat/optim/__init__.py:19-51``)."""
    return _make_tx("sgd", lr, **kwargs)


def Adam(lr: float = 1e-3, **kwargs):
    return _make_tx("adam", lr, **kwargs)


def AdamW(lr: float = 1e-3, **kwargs):
    return _make_tx("adamw", lr, **kwargs)


def Adagrad(lr: float = 1e-2, **kwargs):
    return _make_tx("adagrad", lr, **kwargs)


def Adadelta(lr: float = 1.0, **kwargs):
    return _make_tx("adadelta", lr, **kwargs)


def RMSprop(lr: float = 1e-2, **kwargs):
    return _make_tx("rmsprop", lr, **kwargs)


class DataParallelOptimizer:
    """Thin wrapper over an optax transform (reference ``dp_optimizer.py:834``).

    ``blocking`` is accepted for parity; the fused XLA step always overlaps
    the gradient reduction with the backward pass.

    Two usage modes:

    * attached to :class:`heat_tpu.nn.DataParallel` — the update runs
      inside the trainer's fused step; :meth:`step` stays the historic
      no-op shim.
    * functional over ``DNDarray`` (or jax) parameter pytrees —
      :meth:`apply_gradients` applies the WHOLE update tree in ONE traced
      flush (:func:`heat_tpu.core.fusion.trace_step`, donated optimizer
      state): one cached executable per parameter-tree signature instead
      of one dispatched program per parameter leaf, counted under
      ``op_engine.fusion_step_flushes``.
    """

    def __init__(self, optimizer, blocking: bool = False):
        if isinstance(optimizer, str):
            raise TypeError("pass an optax transform, e.g. ht.optim.SGD(lr=0.01)")
        self.tx = optimizer
        self.blocking = blocking
        self.opt_state = None
        self._net = None
        self._traced_apply = None

    def _attach(self, net) -> None:
        self._net = net

    @staticmethod
    def _unwrap(tree):
        """``DNDarray`` leaves -> their physical jax arrays (optax sees a
        uniform jax pytree; layout metadata stays on the wrapper side)."""
        from ..core.dndarray import DNDarray

        return jax.tree_util.tree_map(
            lambda x: x.larray if isinstance(x, DNDarray) else x, tree,
            is_leaf=lambda x: isinstance(x, DNDarray))

    def reset_state(self, params) -> None:
        self.opt_state = self.tx.init(self._unwrap(params))

    def apply_gradients(self, params, grads):
        """Functional update: ``new_params`` mirroring ``params`` (same
        pytree, same ``DNDarray`` layouts), with ``self.opt_state``
        advanced. The whole tree updates in ONE traced flush — repeat
        calls hit the step program cache; the optimizer-state buffers are
        donated (updated in place). Initializes state lazily on first
        use."""
        from ..core import fusion

        if self.opt_state is None:
            self.reset_state(params)
        if self._traced_apply is None:
            tx = self.tx
            unwrap = self._unwrap

            def _apply(params, opt_state, grads):
                import optax

                p, g = unwrap(params), unwrap(grads)
                updates, opt_state = tx.update(g, opt_state, p)
                new_p = optax.apply_updates(p, updates)
                # re-wrap: each new leaf inherits its parameter's layout
                from ..core.dndarray import DNDarray

                def rewrap(old, new):
                    if isinstance(old, DNDarray):
                        return DNDarray(new, old.gshape, old.dtype,
                                        old.split, old.device, old.comm)
                    return new

                new_params = jax.tree_util.tree_map(
                    rewrap, params, new_p,
                    is_leaf=lambda x: isinstance(x, DNDarray))
                return new_params, opt_state

            self._traced_apply = fusion.trace_step(_apply,
                                                   donate_argnums=(1,))
        new_params, self.opt_state = self._traced_apply(
            params, self.opt_state, grads)
        return new_params

    def step(self, params=None, grads=None):
        """With ``(params, grads)``: one batched functional update
        (:meth:`apply_gradients`). Argless: the historic no-op shim
        (reference defers step in non-blocking mode ``:861`` — the update
        happens inside the attached trainer's fused train step)."""
        if params is None and grads is None:
            return None
        if params is None or grads is None:
            raise TypeError("step() takes both params and grads (or neither)")
        return self.apply_gradients(params, grads)

    def zero_grad(self) -> None:
        """No-op: functional gradients are never accumulated in place."""
        return None


class DASO:
    """Hierarchical delayed-sync optimizer (reference ``dp_optimizer.py:46``).

    Two-tier data parallelism on a factored ``MeshGrid((slow, fast),
    ("dcn", "ici"))``: the *fast* tier (intra-node, ICI) synchronizes
    gradients every step inside the fused train step; the *slow* tier
    (inter-node, DCN) lets each node-group's parameters **diverge** and
    reconciles them every ``global_skip`` batches by a bfloat16 parameter
    average that is applied ``batches_to_wait`` batches later, blended
    half-and-half with the locally advanced parameters — the XLA rendering
    of the reference's delayed ``_global_sync``/``_gs_rcv_update`` pipeline
    (``:432-652``: Isend of bf16 params, received N batches later, averaged
    into the local model).

    Parameter layout: with a non-trivial slow tier every parameter leaf
    carries a leading replica axis of length ``slow_size``, sharded over the
    ``"dcn"`` mesh axis (:meth:`replicate` installs it, :meth:`unreplicate`
    averages it away). The slow-tier average is then one ``mean`` over that
    axis — GSPMD turns it into the inter-node all-reduce. Warmup / cycling /
    cooldown phases are driven by :class:`DetectMetricPlateau` exactly like
    the reference's ``epoch_loss_logic`` (``:336``).
    """

    def __init__(
        self,
        local_optimizer,
        total_epochs: int,
        comm=None,
        warmup_epochs: int = 4,
        cooldown_epochs: int = 4,
        scheduler=None,
        stability_level: float = 0.05,
        max_global_skips: int = 8,
        sending_chunk_size: int = 10_000_000,
        downcast_type=jnp.bfloat16,
        local_size: Optional[int] = None,
        verbose: bool = False,
    ):
        from ..core.communication import MeshGrid

        self.local_optimizer = (
            local_optimizer
            if isinstance(local_optimizer, DataParallelOptimizer)
            else DataParallelOptimizer(local_optimizer)
        )
        self.comm = sanitize_comm(comm)
        self.total_epochs = total_epochs
        self.warmup_epochs = warmup_epochs
        self.cooldown_epochs = cooldown_epochs
        self.stability = DetectMetricPlateau(patience=2, threshold=stability_level)
        self.max_global_skips = max_global_skips
        self.sending_chunk_size = sending_chunk_size
        self.downcast_type = downcast_type
        self.verbose = verbose

        # two-level mesh: nodes (slow/DCN) × devices-per-node (fast/ICI).
        # The reference reads node boundaries from MPI topology
        # (``dp_optimizer.py:136-170``); here they come from the process
        # count on a real pod, or from ``local_size`` explicitly.
        n = self.comm.size
        if local_size is None:
            local_size = max(1, n // jax.process_count())
        if n % local_size:
            raise ValueError(
                f"mesh of {n} devices cannot factor into nodes of {local_size}")
        self.slow_size = n // local_size
        self.fast_size = local_size
        self.grid = MeshGrid((self.slow_size, self.fast_size), ("dcn", "ici"),
                             devices=self.comm.devices)

        self.global_skip = 1
        self.batches_to_wait = 1
        self.epoch = 0
        self._batch = 0
        self._pending = None  # (apply_at_batch, bf16 slow-tier average)
        self._avg_fn = None
        self._blend_fn = None
        # (fusion.quant_key(), fusion.chunk_key(), fusion.hier_key()) ->
        # (packed capture program, its qinfo dict): codec/chunk/tier
        # toggles compile siblings, toggle-back re-hits the cached
        # exact/unchunked/flat program (same discipline as the model
        # step caches)
        self._packed_avgs = {}

    @property
    def tx(self):
        return self.local_optimizer.tx

    # -------------------------------------------------------------- #
    # replica-axis layout                                            #
    # -------------------------------------------------------------- #
    def replicate(self, params):
        """Install the slow-tier replica axis: every leaf becomes
        ``(slow_size, *shape)``, sharded over the ``"dcn"`` mesh axis and
        replicated over ``"ici"`` (reference: per-node model copies)."""
        slow = self.slow_size

        def rep(p):
            p = jnp.asarray(p)
            out = jnp.broadcast_to(p[None], (slow,) + p.shape)
            return jax.device_put(out, self.grid.sharding(out.ndim, dcn=0))

        return jax.tree_util.tree_map(rep, params)

    def unreplicate(self, params):
        """Collapse the replica axis by averaging (end-of-training export)."""
        return jax.tree_util.tree_map(
            lambda p: jnp.mean(p, axis=0) if jnp.issubdtype(p.dtype, jnp.floating)
            else p[0],
            params)

    # -------------------------------------------------------------- #
    def _build_sync_fns(self):
        cast = self.downcast_type

        if self.slow_size == 1:
            # trivial slow tier: the only replica's "sync" is the bf16 wire
            # round-trip. Works for plain (un-replicated) params too — the
            # single-host convenience mode.
            self._avg_fn = jax.jit(lambda ps: ps)
            self._blend_fn = jax.jit(lambda av, ps: jax.tree_util.tree_map(
                lambda p: p.astype(cast).astype(p.dtype)
                if jnp.issubdtype(p.dtype, jnp.floating) else p, ps))
            return

        def avg_leaf(p):
            if not jnp.issubdtype(p.dtype, jnp.floating):
                return p[0]
            # bf16 wire format: downcast before the inter-node reduction
            # (reference ``__prep_params_to_send`` ``:592``)
            return jnp.mean(p.astype(cast), axis=0)

        def blend_leaf(a, p):
            if not jnp.issubdtype(p.dtype, jnp.floating):
                return p
            return ((a.astype(p.dtype)[None] + p) * 0.5).astype(p.dtype)

        self._avg_fn = jax.jit(
            lambda ps: jax.tree_util.tree_map(avg_leaf, ps))
        self._blend_fn = jax.jit(
            lambda av, ps: jax.tree_util.tree_map(blend_leaf, av, ps))

    def _build_packed_avg(self, quant=None, chunks=None, hier=None):
        """The packed (and quantizable) form of the slow-tier capture: ONE
        ``shard_map`` over the ``"dcn"`` axis combining EVERY leaf's bf16
        wire average in a single flattened collective
        (:func:`heat_tpu.core.fusion.packed_psum` — which rewrites the
        qualifying payloads under ``HEAT_TPU_QUANT_COLLECTIVES``), instead
        of the one GSPMD all-reduce per parameter leaf the jitted
        ``tree_map`` mean emits. Wire semantics match the reference DASO
        contract exactly: parameters downcast to bf16 BEFORE the
        inter-node reduction (``__prep_params_to_send`` ``:592``).

        Under ``HEAT_TPU_HIER`` the replicas are declared REPLICATED over
        the fast ``"ici"`` axis (every device in a node group holds the
        same replica), so the hierarchical exchange shards the DCN wire
        payload over the node's devices: each device slices its own 1/ici
        tile (zero collectives — the data already agrees), all-reduces
        only that tile across DCN, and an ICI all-gather reassembles —
        per-device DCN bytes drop by the ici factor."""
        from ..core import fusion
        from ..core._compat import shard_map
        from jax.sharding import PartitionSpec as P

        cast = self.downcast_type
        slow = self.slow_size
        qinfo = {}
        if quant is None:
            quant = fusion.quant_key()
        if chunks is None:
            chunks = fusion.chunk_key()
        if hier is None:
            hier = fusion.hier_key()
        replicated = ("ici",) if (hier[0] and self.fast_size > 1) else ()

        def body(params):
            fusion.reset_qinfo(qinfo)
            leaves, treedef = jax.tree_util.tree_flatten(params)
            # local block is (1, ...): this device's replica in wire dtype
            parts = [l[0].astype(cast) for l in leaves]
            packed = fusion.packed_psum(parts, ("dcn",), qinfo=qinfo,
                                        quant=quant, chunks=chunks,
                                        hier=hier, replicated=replicated)
            return jax.tree_util.tree_unflatten(
                treedef, [(p / slow).astype(cast) for p in packed])

        sm = shard_map(body, mesh=self.grid.mesh,
                       in_specs=(P("dcn"),), out_specs=P(),
                       check_vma=False)
        return jax.jit(sm), qinfo

    def _capture(self, params):
        """The slow-tier capture (the bf16 "send"): the packed/quantized
        shard_map form when the fusion step engine is on and every leaf is
        floating (non-float leaves need the legacy replica-0 pick), else
        the historic per-leaf jitted mean. Keyed on
        (:func:`heat_tpu.core.fusion.quant_key`,
        :func:`heat_tpu.core.fusion.chunk_key`) so a codec or chunk-count
        toggle rebuilds instead of dispatching a stale wire format or leg
        structure."""
        from ..core import fusion

        if (self.slow_size > 1 and fusion.step_enabled()
                and all(jnp.issubdtype(l.dtype, jnp.floating)
                        for l in jax.tree_util.tree_leaves(params)
                        if hasattr(l, "dtype"))):
            key = (fusion.quant_key(), fusion.chunk_key(),
                   fusion.hier_key())
            if key not in self._packed_avgs:
                self._packed_avgs[key] = self._build_packed_avg(*key)
            fn, qinfo = self._packed_avgs[key]
            out = fn(params)
            fusion.tick_quant(qinfo)
            return out
        return self._avg_fn(params)

    def _check_replicated(self, params):
        """Reject un-replicated params when the slow tier is real: the
        replica average would otherwise silently mean over a *parameter*
        axis (round-2 review finding)."""
        if self.slow_size == 1:
            return
        slow = self.slow_size
        bad = [
            p.shape
            for p in jax.tree_util.tree_leaves(params)
            if not (hasattr(p, "ndim") and p.ndim >= 1 and p.shape[0] == slow)
        ]
        if bad:
            raise ValueError(
                f"DASO with slow_size={slow} requires the replica axis on "
                f"every parameter leaf (use daso.replicate(params)); got "
                f"leaf shapes {bad[:3]}")

    def _global_sync(self, params):
        """Immediate slow-tier reconciliation (capture + blend in one step;
        the scheduled path in :meth:`step` splits these by
        ``batches_to_wait``)."""
        if self._avg_fn is None:
            self._build_sync_fns()
        self._check_replicated(params)
        return self._blend_fn(self._capture(params), params)

    def step(self, params):
        """Advance the DASO schedule by one batch (reference ``step``
        ``:730``): apply a previously captured slow-tier average once its
        delay expires, and capture a new one every ``global_skip`` batches.

        ``params`` must carry the replica axis (:meth:`replicate`) when
        ``slow_size > 1``.
        """
        if self._avg_fn is None:
            self._build_sync_fns()
        self._check_replicated(params)
        self._batch += 1
        if self._pending is not None and self._batch >= self._pending[0]:
            params = self._blend_fn(self._pending[1], params)
            self._pending = None
        skip = max(1, self.global_skip)
        if self._batch % skip == 0:
            avg = self._capture(params)  # the bf16 "send"
            wait = min(self.batches_to_wait, skip)
            if wait <= 0:
                params = self._blend_fn(avg, params)
            else:
                # received ``wait`` batches later, averaged into the locally
                # advanced parameters (reference ``_gs_rcv_update`` ``:652``)
                self._pending = (self._batch + wait, avg)
        return params

    def epoch_loss_logic(self, loss) -> None:
        """Adjust the skip cadence from the loss plateau signal
        (reference ``epoch_loss_logic`` ``:336``)."""
        self.epoch += 1
        loss = float(loss)
        if self.epoch <= self.warmup_epochs:
            self.global_skip = 1
        elif self.epoch > self.total_epochs - self.cooldown_epochs:
            self.global_skip = 1
        elif self.stability.test_if_improving(loss):
            self.global_skip = min(self.max_global_skips, self.global_skip * 2)
            if self.verbose:
                print(f"DASO: loss plateau → global_skip={self.global_skip}")
        return None
