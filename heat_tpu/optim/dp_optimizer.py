"""Data-parallel optimizers (reference ``heat/optim/dp_optimizer.py``).

``DataParallelOptimizer`` (reference ``:834-877``) wraps a local optimizer
and defers ``step()`` into the fused train step. ``DASO`` (reference
``:46-833``) is the hierarchical **Distributed Asynchronous & Selective
Optimization** scheme: node-local sync every batch, global sync every
``global_skips`` batches with gradients downcast to bf16 for the wire
(the reference needs custom MPI reduce ops for that, ``:21-43`` — bf16 is a
native reduce dtype on TPU ICI). The TPU analogue keeps DASO's *schedule*
(skipped global syncs, bf16 wire format, plateau-driven phase changes) on a
two-level mesh: the fast axis is intra-node ICI, the slow axis is the
DCN/inter-node dimension.
"""

from __future__ import annotations

from typing import Callable, Optional

import numpy as np

import jax
import jax.numpy as jnp
import optax

from ..core.communication import sanitize_comm
from .utils import DetectMetricPlateau

__all__ = ["DataParallelOptimizer", "DASO", "SGD", "Adam", "AdamW", "Adagrad", "Adadelta", "RMSprop"]


def _make_tx(name: str, lr: float, **kwargs):
    table = {
        "sgd": lambda: optax.sgd(lr, momentum=kwargs.get("momentum", 0.0), nesterov=kwargs.get("nesterov", False)),
        "adam": lambda: optax.adam(lr, b1=kwargs.get("b1", 0.9), b2=kwargs.get("b2", 0.999)),
        "adamw": lambda: optax.adamw(lr, weight_decay=kwargs.get("weight_decay", 1e-4)),
        "adagrad": lambda: optax.adagrad(lr),
        "adadelta": lambda: optax.adadelta(lr),
        "rmsprop": lambda: optax.rmsprop(lr),
    }
    return table[name]()


def SGD(lr: float = 0.01, **kwargs):
    """torch.optim.SGD-style constructor → optax (reference optim passthrough,
    ``heat/optim/__init__.py:19-51``)."""
    return _make_tx("sgd", lr, **kwargs)


def Adam(lr: float = 1e-3, **kwargs):
    return _make_tx("adam", lr, **kwargs)


def AdamW(lr: float = 1e-3, **kwargs):
    return _make_tx("adamw", lr, **kwargs)


def Adagrad(lr: float = 1e-2, **kwargs):
    return _make_tx("adagrad", lr, **kwargs)


def Adadelta(lr: float = 1.0, **kwargs):
    return _make_tx("adadelta", lr, **kwargs)


def RMSprop(lr: float = 1e-2, **kwargs):
    return _make_tx("rmsprop", lr, **kwargs)


class DataParallelOptimizer:
    """Thin wrapper over an optax transform (reference ``dp_optimizer.py:834``).

    ``blocking`` is accepted for parity; the fused XLA step always overlaps
    the gradient reduction with the backward pass.
    """

    def __init__(self, optimizer, blocking: bool = False):
        if isinstance(optimizer, str):
            raise TypeError("pass an optax transform, e.g. ht.optim.SGD(lr=0.01)")
        self.tx = optimizer
        self.blocking = blocking
        self.opt_state = None
        self._net = None

    def _attach(self, net) -> None:
        self._net = net

    def reset_state(self, params) -> None:
        self.opt_state = self.tx.init(params)

    def step(self) -> None:
        """No-op shim (reference defers step in non-blocking mode ``:861``):
        the update happens inside the fused train step."""
        return None

    def zero_grad(self) -> None:
        """No-op: functional gradients are never accumulated in place."""
        return None


class DASO:
    """Hierarchical delayed-sync optimizer (reference ``dp_optimizer.py:46``).

    Two-tier schedule on a factored mesh: a *fast* tier (intra-node, ICI)
    that synchronizes every step inside the fused train step, and a *slow*
    tier (inter-node) that synchronizes parameters every ``global_skip``
    steps, in bfloat16. Warmup / cycling / cooldown phases are driven by
    :class:`DetectMetricPlateau` exactly like the reference's
    ``epoch_loss_logic`` (``:336``).

    On a single-host mesh the slow tier spans a device sub-grid; the
    schedule (and its numerics: bf16 wire, skip cadence) is identical.
    """

    def __init__(
        self,
        local_optimizer,
        total_epochs: int,
        comm=None,
        warmup_epochs: int = 4,
        cooldown_epochs: int = 4,
        scheduler=None,
        stability_level: float = 0.05,
        max_global_skips: int = 8,
        sending_chunk_size: int = 10_000_000,
        downcast_type=jnp.bfloat16,
        verbose: bool = False,
    ):
        self.local_optimizer = (
            local_optimizer
            if isinstance(local_optimizer, DataParallelOptimizer)
            else DataParallelOptimizer(local_optimizer)
        )
        self.comm = sanitize_comm(comm)
        self.total_epochs = total_epochs
        self.warmup_epochs = warmup_epochs
        self.cooldown_epochs = cooldown_epochs
        self.stability = DetectMetricPlateau(patience=2, threshold=stability_level)
        self.max_global_skips = max_global_skips
        self.sending_chunk_size = sending_chunk_size
        self.downcast_type = downcast_type
        self.verbose = verbose

        self.global_skip = 1
        self.batches_to_wait = 1
        self.epoch = 0
        self._batch = 0
        self._sync_fn = None

    @property
    def tx(self):
        return self.local_optimizer.tx

    # -------------------------------------------------------------- #
    def _global_sync(self, params):
        """Slow-tier parameter averaging in bf16 (reference ``_global_sync``
        ``:432`` + ``_gs_send_params`` ``:592``)."""
        cast = self.downcast_type

        def avg(p):
            return jnp.mean(
                jnp.stack([p.astype(cast)]), axis=0
            ).astype(p.dtype)

        # parameters are replicated on the mesh: averaging across replicas is
        # the identity *unless* tiers diverged; we re-broadcast the bf16 cast
        # to model the wire format.
        return jax.tree_util.tree_map(lambda p: p.astype(cast).astype(p.dtype), params)

    def step(self, params):
        """Advance the DASO schedule by one batch (reference ``step`` ``:730``)."""
        self._batch += 1
        if self._batch % max(1, self.global_skip) == 0:
            params = self._global_sync(params)
        return params

    def epoch_loss_logic(self, loss) -> None:
        """Adjust the skip cadence from the loss plateau signal
        (reference ``epoch_loss_logic`` ``:336``)."""
        self.epoch += 1
        loss = float(loss)
        if self.epoch <= self.warmup_epochs:
            self.global_skip = 1
        elif self.epoch > self.total_epochs - self.cooldown_epochs:
            self.global_skip = 1
        elif self.stability.test_if_improving(loss):
            self.global_skip = min(self.max_global_skips, self.global_skip * 2)
            if self.verbose:
                print(f"DASO: loss plateau → global_skip={self.global_skip}")
        return None
