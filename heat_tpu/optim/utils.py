"""Optimizer utilities (reference ``heat/optim/utils.py``)."""

from __future__ import annotations

from typing import Dict

__all__ = ["DetectMetricPlateau"]


class DetectMetricPlateau:
    """Plateau detector with checkpointable state
    (reference ``utils.py:14-117``)."""

    def __init__(
        self,
        mode: str = "min",
        patience: int = 10,
        threshold: float = 1e-4,
        threshold_mode: str = "rel",
        cooldown: int = 0,
    ):
        if mode not in ("min", "max"):
            raise ValueError(f"mode {mode} is unknown!")
        if threshold_mode not in ("rel", "abs"):
            raise ValueError(f"threshold mode {threshold_mode} is unknown!")
        self.mode = mode
        self.patience = patience
        self.threshold = threshold
        self.threshold_mode = threshold_mode
        self.cooldown = cooldown
        self.cooldown_counter = 0
        self.num_bad_epochs = 0
        self.best = float("inf") if mode == "min" else -float("inf")
        self.last_epoch = 0

    def get_state(self) -> Dict:
        """Checkpointable state dict (reference ``utils.py:72``)."""
        return {
            "mode": self.mode,
            "patience": self.patience,
            "threshold": self.threshold,
            "threshold_mode": self.threshold_mode,
            "cooldown": self.cooldown,
            "cooldown_counter": self.cooldown_counter,
            "num_bad_epochs": self.num_bad_epochs,
            "best": self.best,
            "last_epoch": self.last_epoch,
        }

    def set_state(self, dic: Dict) -> None:
        """Restore from a state dict (reference ``utils.py:90``)."""
        for key, value in dic.items():
            setattr(self, key, value)

    def is_better(self, a, best) -> bool:
        if self.mode == "min" and self.threshold_mode == "rel":
            return a < best * (1.0 - self.threshold)
        if self.mode == "min":
            return a < best - self.threshold
        if self.threshold_mode == "rel":
            return a > best * (1.0 + self.threshold)
        return a > best + self.threshold

    def test_if_improving(self, metrics) -> bool:
        """True when the metric has plateaued (reference ``utils.py:108``)."""
        current = float(metrics)
        self.last_epoch += 1

        if self.is_better(current, self.best):
            self.best = current
            self.num_bad_epochs = 0
        else:
            self.num_bad_epochs += 1

        if self.cooldown_counter > 0:
            self.cooldown_counter -= 1
            self.num_bad_epochs = 0

        if self.num_bad_epochs > self.patience:
            self.cooldown_counter = self.cooldown
            self.num_bad_epochs = 0
            return True
        return False
