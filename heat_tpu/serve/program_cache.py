"""Compiled-program cache for the serving path.

One executable per ``(callable, bucket shape, dtype, mesh)`` — the serving
analogue of the resharding plan cache (``core/resharding.py``): a bounded
key space (the bucket ladder is finite), explicit hit/miss/compile
counters, and a hard observable for the steady-state guarantee that
traffic triggers **zero recompiles** after warmup (asserted in
``tests/test_serve.py``, same spirit as ``RESPLIT_AUDIT.json``).

Programs are ahead-of-time compiled (``jit(fn).lower(aval).compile()``) so
the *compile* happens at cache-miss time — during warmup — and never
inside a latency-sensitive request. Callables that cannot lower from
abstract values alone fall back to the plain ``jax.jit`` wrapper (XLA's
own shape-keyed cache then provides the same reuse; the counters still
track bucket-level misses).

Counters are mirrored into the process-wide registry
(:mod:`heat_tpu.utils.metrics`: ``serve.program_hits`` /
``serve.program_misses`` / ``serve.program_compiles``) so
``ht.runtime_stats()`` sees every cache in one snapshot.
"""

from __future__ import annotations

import threading
from typing import Any, Callable, Dict, Tuple

import jax

from ..utils import metrics as _metrics

__all__ = ["ProgramCache"]


class ProgramCache:
    """Shape-keyed cache of compiled serving programs."""

    def __init__(self, name: str = "serve", aot: bool = True):
        self.name = name
        self.aot = aot
        self._programs: Dict[Tuple, Callable] = {}
        self._lock = threading.Lock()
        self.hits = 0
        self.misses = 0
        self.compiles = 0

    def get(self, fn: Callable, shape: Tuple[int, ...], dtype,
            token: Any = ()) -> Callable:
        """The compiled program for ``fn`` at input aval ``(shape, dtype)``.

        ``token`` folds any extra identity into the key — executors pass
        the mesh/communicator cache key, so the same callable served over
        two meshes gets two programs.
        """
        key = (fn, tuple(int(s) for s in shape), str(dtype), token)
        with self._lock:
            prog = self._programs.get(key)
            if prog is not None:
                self.hits += 1
                _metrics.inc("serve.program_hits")
                return prog
            self.misses += 1
            _metrics.inc("serve.program_misses")
        # compile OUTSIDE the lock: a multi-second XLA compile must not
        # serialize unrelated lookups. A rare double-compile of the same
        # key is benign (last writer wins; counters record both).
        prog = self._compile(fn, shape, dtype)
        with self._lock:
            self._programs[key] = prog
            self.compiles += 1
        _metrics.inc("serve.program_compiles")
        return prog

    def _compile(self, fn, shape, dtype) -> Callable:
        jitted = jax.jit(fn)
        if self.aot:
            try:
                aval = jax.ShapeDtypeStruct(tuple(shape), dtype)
                return jitted.lower(aval).compile()
            except Exception:
                # not lowerable from abstract avals (e.g. value-dependent
                # python in fn) — the jit wrapper still shape-caches
                pass
        return jitted

    def stats(self) -> dict:
        """Plain-dict counters (folded into metrics snapshots)."""
        with self._lock:
            return {"hits": self.hits, "misses": self.misses,
                    "compiles": self.compiles,
                    "entries": len(self._programs)}

    def reset(self) -> None:
        with self._lock:
            self._programs.clear()
            self.hits = 0
            self.misses = 0
            self.compiles = 0

    def __len__(self) -> int:
        with self._lock:
            return len(self._programs)

    def __repr__(self) -> str:
        s = self.stats()
        return (f"ProgramCache({self.name!r}, entries={s['entries']}, "
                f"hits={s['hits']}, misses={s['misses']})")
