"""Compiled-program cache for the serving path.

One executable per ``(callable, bucket shape, dtype, mesh)`` — the serving
analogue of the resharding plan cache (``core/resharding.py``): a bounded
key space (the bucket ladder is finite), explicit hit/miss/compile
counters, and a hard observable for the steady-state guarantee that
traffic triggers **zero recompiles** after warmup (asserted in
``tests/test_serve.py``, same spirit as ``RESPLIT_AUDIT.json``).

The implementation was generalized into
:mod:`heat_tpu.utils.program_cache` when the op-chain fusion engine
(:mod:`heat_tpu.core.fusion`) needed the same contract; this module keeps
every historical ``heat_tpu.serve.program_cache`` import path working AND
pins the mirrored-counter namespace to ``serve.program_hits`` /
``_misses`` / ``_compiles`` regardless of the cache's display name — the
adapters build executors with per-model cache names ("transformer", the
estimator class), and the ladder's per-test ``serve_program_compiles``
log line (NEXT.md §2b correlation) must keep counting all of them under
one family, as it always has.
"""

from __future__ import annotations

from ..utils.program_cache import ProgramCache as _ProgramCache

__all__ = ["ProgramCache"]


class ProgramCache(_ProgramCache):
    """Serving-path program cache: display name is per-model, counters
    always aggregate under ``serve.program_*``."""

    def __init__(self, name: str = "serve", aot: bool = True):
        super().__init__(name=name, aot=aot, counter_prefix="serve")
