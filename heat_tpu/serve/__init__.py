"""heat_tpu.serve — batched, backpressured inference serving.

The request path between user traffic and the sharded models: a
:class:`ServingExecutor` coalesces per-request arrays into micro-batches,
pads them onto a finite shape-bucket ladder (:mod:`~heat_tpu.serve.bucketing`),
runs one compiled sharded program per batch from a counter-instrumented
:class:`ProgramCache`, and scatters results to per-request futures — with
bounded admission (:class:`ServeOverloaded`), per-request deadlines
(:class:`ServeDeadlineExceeded`), a drain/close lifecycle and a degraded
single-request fallback. For autoregressive LLM traffic, the
:class:`DecodeEngine` (:mod:`heat_tpu.serve.decode`) replaces batch
coalescing with continuous batching: a slot-based device-resident KV
cache with in-flight request join/leave and ONE cached decode-step
executable. ``heat_tpu.serve.metrics.runtime_stats`` (exported
as ``ht.runtime_stats()``) is the process's one observability surface.

>>> import heat_tpu as ht
>>> from heat_tpu.serve import serve_estimator
>>> est = ht.cluster.KMeans(n_clusters=8).fit(x)
>>> ex = serve_estimator(est)
>>> ex.warmup(feat_shape=(64,), rows=range(1, 17))
>>> labels = ex.predict(batch_rows)          # or ex.submit(...) -> Future
>>> ex.stats()["latency_ms"]["p99"]

Model adapters (transformer forward, sklearn-layer estimators) live in
:mod:`heat_tpu.serve.adapters`; they are imported lazily so ``import
heat_tpu`` does not pay for the model stacks.
"""

from . import admission
from . import bucketing
from . import errors
from . import loadgen
from . import metrics
from .admission import AdmissionController, Tenant
from .bucketing import FixedBuckets, Pow2Buckets
from .errors import (ServeCircuitOpen, ServeClosed, ServeDeadlineExceeded,
                     ServeError, ServeOverloaded, ServeRateLimited)
from .decode import DecodeConfig, DecodeEngine, live_decode_engines
from .executor import ServeConfig, ServingExecutor, live_executors
from .loadgen import TenantLoad, estimate_capacity, run_open_loop
from .metrics import ServeMetrics, runtime_stats
from .program_cache import ProgramCache

__all__ = [
    "ServingExecutor",
    "ServeConfig",
    "DecodeEngine",
    "DecodeConfig",
    "live_decode_engines",
    "ProgramCache",
    "ServeMetrics",
    "Pow2Buckets",
    "FixedBuckets",
    "AdmissionController",
    "Tenant",
    "TenantLoad",
    "run_open_loop",
    "estimate_capacity",
    "ServeError",
    "ServeOverloaded",
    "ServeRateLimited",
    "ServeCircuitOpen",
    "ServeDeadlineExceeded",
    "ServeClosed",
    "runtime_stats",
    "live_executors",
    # lazy (module __getattr__): adapters and its helpers
    "adapters",
    "serve_transformer",
    "serve_estimator",
    "transformer_logits_fn",
    "estimator_predict_fn",
]

_LAZY_ADAPTERS = ("serve_transformer", "serve_estimator",
                  "transformer_logits_fn", "estimator_predict_fn")


def __getattr__(name):
    # adapters pull in nn/cluster/classification — loaded on first use only
    # (importlib, not ``from . import``: the latter re-enters this
    # __getattr__ through hasattr and recurses)
    if name == "adapters" or name in _LAZY_ADAPTERS:
        import importlib

        adapters = importlib.import_module(".adapters", __name__)
        return adapters if name == "adapters" else getattr(adapters, name)
    raise AttributeError(f"module 'heat_tpu.serve' has no attribute {name!r}")
