"""Typed serving errors — the executor's robustness contract.

Every failure mode a caller can act on has its own type, so admission
control (`except ServeOverloaded: retry elsewhere`), deadline handling and
shutdown races are distinguishable without string matching. All inherit
:class:`ServeError`; :class:`ServeDeadlineExceeded` is also a
``TimeoutError`` so generic timeout handlers catch it.
"""

from __future__ import annotations

__all__ = [
    "ServeError",
    "ServeOverloaded",
    "ServeRateLimited",
    "ServeCircuitOpen",
    "ServeDeadlineExceeded",
    "ServeClosed",
]


class ServeError(RuntimeError):
    """Base class for serving-path errors."""


class ServeOverloaded(ServeError):
    """The bounded request queue is full — the request was load-shed at
    admission (backpressure). The caller should retry with backoff or route
    to another replica; the executor did NOT enqueue anything."""


class ServeRateLimited(ServeError):
    """The tenant's token bucket is empty — the request was rejected at
    admission without touching the queue. The sustained rate for this
    tenant exceeds its registered ``rate_limit``; the caller should back
    off (the bucket refills continuously at ``rate_limit`` tokens/s)."""


class ServeCircuitOpen(ServeError):
    """The tenant's circuit breaker is open — recent batch dispatches for
    this tenant failed persistently, so its requests fast-fail at
    admission instead of burning the worker's dispatch-retry budget (and
    starving healthy tenants). The breaker lets a bounded number of probe
    requests through after its cool-down; a successful probe closes it."""


class ServeDeadlineExceeded(ServeError, TimeoutError):
    """The request's deadline expired while it was still queued — it was
    dropped without running (no compute is wasted on an answer nobody is
    waiting for)."""


class ServeClosed(ServeError):
    """The executor is closed (or closing): no new requests are accepted,
    and — on a non-draining close — pending requests fail with this."""
