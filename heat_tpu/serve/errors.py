"""Typed serving errors — the executor's robustness contract.

Every failure mode a caller can act on has its own type, so admission
control (`except ServeOverloaded: retry elsewhere`), deadline handling and
shutdown races are distinguishable without string matching. All inherit
:class:`ServeError`; :class:`ServeDeadlineExceeded` is also a
``TimeoutError`` so generic timeout handlers catch it.
"""

from __future__ import annotations

__all__ = [
    "ServeError",
    "ServeOverloaded",
    "ServeDeadlineExceeded",
    "ServeClosed",
]


class ServeError(RuntimeError):
    """Base class for serving-path errors."""


class ServeOverloaded(ServeError):
    """The bounded request queue is full — the request was load-shed at
    admission (backpressure). The caller should retry with backoff or route
    to another replica; the executor did NOT enqueue anything."""


class ServeDeadlineExceeded(ServeError, TimeoutError):
    """The request's deadline expired while it was still queued — it was
    dropped without running (no compute is wasted on an answer nobody is
    waiting for)."""


class ServeClosed(ServeError):
    """The executor is closed (or closing): no new requests are accepted,
    and — on a non-draining close — pending requests fail with this."""
