"""The batched, backpressured serving executor.

The request path the ROADMAP's "serves heavy traffic" north star needs and
the reference framework never had: callers submit per-request host arrays
from any thread; a single background worker coalesces them into
micro-batches, pads each batch onto a shape bucket, runs ONE compiled
sharded program from the :class:`~heat_tpu.serve.program_cache.ProgramCache`,
and scatters the result rows back onto per-request futures.

Design points, in the order they matter in production:

* **Bounded admission.** ``submit`` never blocks and never queues beyond
  ``queue_limit`` — an overloaded executor sheds at the door with a typed
  :class:`~heat_tpu.serve.errors.ServeOverloaded` instead of growing an
  unbounded backlog (queueing theory: past saturation, queue growth only
  adds latency, never throughput).
* **Multi-tenant admission (opt-in).** ``register_tenant(name, priority=,
  slo_ms=, max_queue=, rate_limit=)`` arms an
  :class:`~heat_tpu.serve.admission.AdmissionController`: the queue
  becomes priority-ordered (higher-priority tenants served first, FIFO
  within a priority; a full queue evicts the youngest strictly-lower-
  priority request rather than shedding the incoming one), per-tenant
  quotas stop one tenant filling the shared bound, token buckets shed
  with :class:`ServeRateLimited`, a per-tenant circuit breaker fast-fails
  with :class:`ServeCircuitOpen` while a persistently failing dispatch
  path cools down, and an EWMA service estimator **early-sheds** queued
  requests that provably cannot meet their deadline before they consume a
  batch slot. With no tenant registered, nothing here runs: the executor
  is byte-for-byte the single-FIFO PR 2 path (same counters, same
  semantics — pinned by ``tests/test_serve.py`` unmodified).
* **Micro-batching.** The worker takes the oldest request, then coalesces
  up to ``max_batch`` compatible requests (same trailing shape + dtype),
  waiting at most ``max_wait_ms`` for stragglers. Rows concatenate along
  axis 0 and zero-pad to the bucket, so every mix of request sizes maps
  onto the same finite set of compiled programs.
* **One dispatch thread.** Only the worker thread touches the device —
  concurrent dispatch is where the XLA:CPU in-process rendezvous deadlocks
  (see ``heat_tpu/__init__.py``), and on TPU it serializes anyway.
* **Deadlines.** A request whose deadline expires while queued is dropped
  without running (:class:`ServeDeadlineExceeded`); compute is never spent
  on an answer nobody is waiting for.
* **Degraded single-request fallback.** With ``batching=False``, or when a
  batch's bucket would exceed ``max_bucket_bytes``, requests run one at a
  time (over-cap singles run at their exact shape — trading the bucket
  ladder's compile reuse for bounded memory).
* **Lifecycle.** ``close(drain=True)`` stops admission and answers what is
  already queued; ``close(drain=False)`` fails pending requests with
  :class:`ServeClosed`. The executor is a context manager.
"""

from __future__ import annotations

import itertools
import threading
import time
import weakref
from concurrent.futures import Future, InvalidStateError
from dataclasses import dataclass
from typing import Any, Callable, Optional, Sequence

import numpy as np

import jax

from .bucketing import Pow2Buckets, bucket_nbytes
from .errors import (ServeCircuitOpen, ServeClosed, ServeDeadlineExceeded,
                     ServeError, ServeOverloaded, ServeRateLimited)
from .metrics import DEFAULT as _DEFAULT_METRICS, ServeMetrics
from .program_cache import ProgramCache

__all__ = ["ServeConfig", "ServingExecutor", "live_executors"]

# live executors (weak): runtime_stats() folds their queue depth and
# program-cache counters into the one observability snapshot
_EXECUTORS: "weakref.WeakSet[ServingExecutor]" = weakref.WeakSet()


def live_executors():
    return list(_EXECUTORS)


@dataclass
class ServeConfig:
    """Executor policy knobs (all host-side; none affect results)."""

    max_batch: int = 16                 # max requests coalesced per program run
    max_wait_ms: float = 2.0            # straggler wait once a batch has begun
    queue_limit: int = 128              # admission bound -> ServeOverloaded
    default_deadline_ms: Optional[float] = None  # per-request override wins
    batching: bool = True               # False -> degraded single-request path
    min_rows: int = 1                   # bucket floor (mesh divisibility)
    bucket_rows: Optional[Callable[[int], int]] = None  # rows -> bucket rows
    max_bucket_bytes: Optional[int] = None  # memory cap -> single-request path

    def __post_init__(self):
        if self.max_batch < 1:
            raise ValueError(f"max_batch must be >= 1, got {self.max_batch}")
        if self.queue_limit < 1:
            raise ValueError(
                f"queue_limit must be >= 1, got {self.queue_limit}")
        if self.bucket_rows is None:
            self.bucket_rows = Pow2Buckets(min_rows=self.min_rows)


# FIFO tiebreaker within a priority level (CPython next() is atomic)
_SEQ = itertools.count()


class _Request:
    # clock discipline: enq_t and deadline_t are BOTH time.monotonic()
    # stamps — the early-shed estimate and _expire compare against the
    # same clock end-to-end; never mix in time.time() here (a wall-clock
    # jump would dispatch expired requests or shed live ones)
    __slots__ = ("x", "rows", "group", "enq_t", "deadline_t", "future",
                 "started", "tenant", "priority", "seq")

    def __init__(self, x: np.ndarray, deadline_t: Optional[float],
                 tenant: Optional[str] = None, priority: int = 0):
        self.x = x
        self.rows = x.shape[0]
        self.group = (x.shape[1:], x.dtype.str)
        self.enq_t = time.monotonic()
        self.deadline_t = deadline_t
        self.future = Future()
        self.started = False  # set_running_or_notify_cancel already called
        self.tenant = tenant
        self.priority = priority
        self.seq = next(_SEQ)


class ServingExecutor:
    """Micro-batching inference front end for one model callable.

    Parameters
    ----------
    model_fn : callable
        ``batch -> result``: takes one ``(bucket_rows, *feat)`` array and
        returns an array (or pytree of arrays) whose leaves all carry the
        batch dimension first. Must be shape-polymorphic only across the
        bucket ladder (it is traced/compiled once per bucket) and
        row-independent — row ``i`` of the output must depend only on row
        ``i`` of the input, which is what makes scatter-back exact.
        Adapters for the transformer LM and the sklearn-layer estimators
        live in :mod:`heat_tpu.serve.adapters`.
    config : ServeConfig, optional
    cache_token : hashable, optional
        Extra program-cache key material — pass the communicator/mesh
        ``cache_key`` so one callable served over two meshes cannot alias
        compiled programs.
    metrics : ServeMetrics, optional
        Defaults to the process-wide shared registry
        (:data:`heat_tpu.serve.metrics.DEFAULT`).
    program_cache : ProgramCache, optional
        Defaults to a private cache; pass a shared one to pool programs
        across executors of the same model family.

    Always ``close()`` an executor you are done with (or use it as a
    context manager): the worker thread holds a reference to the
    executor, so an abandoned one is never garbage-collected.
    """

    def __init__(self, model_fn: Callable, config: Optional[ServeConfig] = None,
                 *, name: str = "serve", cache_token: Any = (),
                 metrics: Optional[ServeMetrics] = None,
                 program_cache: Optional[ProgramCache] = None):
        self.model_fn = model_fn
        self.config = config if config is not None else ServeConfig()
        self.name = name
        self.cache_token = cache_token
        self.metrics = metrics if metrics is not None else _DEFAULT_METRICS
        self.program_cache = (program_cache if program_cache is not None
                              else ProgramCache(name=name))
        self._q: list = []
        self._cv = threading.Condition()
        self._admission = None  # AdmissionController once a tenant registers
        self._closed = False
        self._draining = False
        self._paused = False
        self._inflight = 0
        self._worker = threading.Thread(
            target=self._run, name=f"heat-serve-{name}", daemon=True)
        self._worker.start()
        _EXECUTORS.add(self)

    # ------------------------------------------------------------------ #
    # submission                                                         #
    # ------------------------------------------------------------------ #
    def submit(self, x, deadline_ms: Optional[float] = None,
               tenant: Optional[str] = None) -> Future:
        """Enqueue one request; returns a ``concurrent.futures.Future``.

        ``x``: ``(rows, *feat)`` host or device array — axis 0 is the
        batchable row axis (a single example is ``rows=1``). The future
        resolves to the model output rows for exactly this request, as
        host (numpy) arrays — the batch output is fetched to host once,
        then each request gets an independent copy of its rows (so no
        result pins the whole batch buffer alive) — or raises one of the
        typed serve errors.

        ``tenant``: requires :meth:`register_tenant` first; the request
        is admitted under that tenant's priority/quota/rate/breaker
        policy, and — when ``deadline_ms`` is not given and the config
        has no default — inherits the tenant's ``slo_ms`` as its
        deadline. With a registry active, ``tenant=None`` rides the
        implicit priority-0 ``"default"`` tenant.
        """
        x = np.asarray(x)
        if x.ndim < 1 or x.shape[0] < 1:
            raise ValueError(
                f"request must have a leading row axis of >= 1, got shape "
                f"{x.shape}")
        adm = self._admission
        if adm is not None:
            tname = adm.resolve(tenant)  # unknown tenant -> ValueError
        elif tenant is not None:
            raise ValueError(
                f"submit(tenant={tenant!r}) needs register_tenant() first")
        else:
            tname = None
        if deadline_ms is None:
            deadline_ms = self.config.default_deadline_ms
            if deadline_ms is None and adm is not None:
                deadline_ms = adm.slo_ms(tname)
        deadline_t = (None if deadline_ms is None
                      else time.monotonic() + deadline_ms / 1e3)
        req = _Request(x, deadline_t, tenant=tname)
        evicted = None
        with self._cv:
            if self._closed:
                raise ServeClosed(f"executor {self.name!r} is closed")
            if adm is None:
                if len(self._q) >= self.config.queue_limit:
                    self.metrics.record_shed()
                    raise ServeOverloaded(
                        f"executor {self.name!r} queue is full "
                        f"({self.config.queue_limit} pending)")
                self._q.append(req)
            else:
                evicted = self._admit(req)
            self._cv.notify_all()
        if evicted is not None:
            # fail the preempted future OUTSIDE the lock (done-callbacks
            # run synchronously — the close() lesson)
            if evicted.future.set_running_or_notify_cancel():
                evicted.future.set_exception(ServeOverloaded(
                    f"executor {self.name!r} queue is full "
                    f"({self.config.queue_limit} pending); preempted by a "
                    f"higher-priority tenant"))
        return req.future

    def register_tenant(self, name: str, *, priority: int = 0,
                        slo_ms: Optional[float] = None,
                        max_queue: Optional[int] = None,
                        rate_limit: Optional[float] = None, **policy):
        """Register a tenant (idempotent; re-registering updates policy)
        and switch admission onto the multi-tenant path. Extra ``policy``
        kwargs: ``burst``, ``breaker_failures``, ``breaker_cooldown_s``,
        ``half_open_max`` (see :class:`~heat_tpu.serve.admission.Tenant`).
        Returns the :class:`Tenant` record."""
        from .admission import AdmissionController

        with self._cv:
            if self._admission is None:
                self._admission = AdmissionController()
            adm = self._admission
        return adm.register(name, priority=priority, slo_ms=slo_ms,
                            max_queue=max_queue, rate_limit=rate_limit,
                            **policy)

    @property
    def admission(self):
        """The executor's ``AdmissionController`` (None until a tenant
        registers — the backward-compatible single-FIFO path)."""
        return self._admission

    def tenant_stats(self) -> dict:
        """Per-tenant counters/breaker snapshot ({} with no registry)."""
        adm = self._admission
        return adm.tenant_stats() if adm is not None else {}

    def _admit(self, req: _Request):
        """Multi-tenant admission (lock held). Returns a preempted queued
        request to fail outside the lock, or None. Raises the typed
        rejection errors; any *machinery* failure degrades this request
        to the legacy bounded-FIFO admission (fail-open: a bug in the new
        admission path must never be an outage the old path lacked)."""
        from ..utils import faults as _faults
        from ..utils import metrics as _pm

        adm = self._admission
        cfg = self.config
        try:
            _faults.check("serve.admission.decide")
            try:
                adm.check_tenant(req.tenant, consume_token=False)
            except ServeCircuitOpen:
                self.metrics.record_breaker_rejected()
                raise
            tenant = adm.get(req.tenant)
            req.priority = int(tenant.priority)  # before the victim scan
            if tenant.max_queue is not None:
                queued = sum(1 for r in self._q if r.tenant == req.tenant)
                if queued >= tenant.max_queue:
                    adm.count(req.tenant, "shed")
                    self.metrics.record_shed()
                    raise ServeOverloaded(
                        f"tenant {req.tenant!r} queue quota is full "
                        f"({tenant.max_queue} pending)")
            # the token is taken LAST among the tenant-local checks so a
            # quota-shed request never drains the bucket (a drained
            # bucket would misattribute later sheds to the rate limit)
            try:
                adm.take_token(req.tenant)
            except ServeRateLimited:
                self.metrics.record_rate_limited()
                raise
            evicted = None
            if len(self._q) >= cfg.queue_limit:
                # preempt the youngest strictly-lower-priority request
                # (scan from the back: the first hit of the minimal
                # priority is the youngest of that priority)
                vi = None
                for i in range(len(self._q) - 1, -1, -1):
                    r = self._q[i]
                    if r.priority < req.priority and (
                            vi is None
                            or r.priority < self._q[vi].priority):
                        vi = i
                if vi is None:
                    adm.refund_token(req.tenant)  # shed: no service taken
                    adm.count(req.tenant, "shed")
                    self.metrics.record_shed()
                    raise ServeOverloaded(
                        f"executor {self.name!r} queue is full "
                        f"({cfg.queue_limit} pending)")
                evicted = self._q.pop(vi)
                adm.count(evicted.tenant, "shed")
                self.metrics.record_shed()
            self._insert(req)
            adm.count(req.tenant, "admitted")
            _pm.inc("serve.admit")
            return evicted
        except ServeError:
            raise    # typed rejections ARE the admission decision
        except Exception:
            # chaos site / machinery failure: legacy bounded-FIFO
            # admission for this request (doc/robustness.md)
            _pm.inc("serve.admission_fallbacks")
            if len(self._q) >= cfg.queue_limit:
                self.metrics.record_shed()
                raise ServeOverloaded(
                    f"executor {self.name!r} queue is full "
                    f"({cfg.queue_limit} pending)")
            self._q.append(req)
            return None

    def _insert(self, req: _Request) -> None:
        """Priority-ordered insert (lock held): descending priority,
        FIFO (seq) within a priority — uniform-priority traffic appends
        in O(1), exactly the legacy order."""
        q = self._q
        key = (-req.priority, req.seq)
        i = len(q)
        while i > 0 and (-q[i - 1].priority, q[i - 1].seq) > key:
            i -= 1
        q.insert(i, req)

    def predict(self, x, deadline_ms: Optional[float] = None,
                timeout: Optional[float] = None,
                tenant: Optional[str] = None):
        """Synchronous convenience: ``submit(...).result(timeout)``."""
        return self.submit(x, deadline_ms=deadline_ms,
                           tenant=tenant).result(timeout)

    def warmup(self, feat_shape: Sequence[int], dtype=np.float32,
               rows: Optional[Sequence[int]] = None) -> dict:
        """Pre-compile the bucket ladder so traffic never pays a compile.

        Submits one zeros request per distinct bucket (sequentially, so
        requests cannot coalesce across buckets) and waits for each.
        Returns the program-cache stats afterwards — steady-state traffic
        over the same ladder must add zero misses from here on.

        The default ``rows`` covers the policy's ladder up to
        ``max_batch * policy.min_rows`` — the reachable buckets when every
        request is ``policy.min_rows`` rows. Callers whose requests carry
        more rows each must pass explicit ``rows`` up to
        ``max_batch * max_request_rows``, or coalesced traffic will still
        reach (and compile) buckets above the default ladder.
        """
        if rows is None:
            policy = self.config.bucket_rows
            ladder = getattr(policy, "ladder", None)
            # the floor that actually shapes the ladder lives on the
            # policy (adapters set it there, not on the config)
            min_rows = max(
                1, int(getattr(policy, "min_rows", self.config.min_rows)))
            rows = (ladder(self.config.max_batch * min_rows)
                    if ladder is not None else [self.config.max_batch])
        feat_shape = tuple(int(s) for s in feat_shape)
        seen = set()
        for r in rows:
            b = self.config.bucket_rows(int(r))
            if b in seen:
                continue
            seen.add(b)
            self.submit(np.zeros((b,) + feat_shape, dtype)).result()
        return self.program_cache.stats()

    # ------------------------------------------------------------------ #
    # lifecycle / introspection                                          #
    # ------------------------------------------------------------------ #
    @property
    def queue_depth(self) -> int:
        with self._cv:
            return len(self._q) + self._inflight

    def pause(self) -> None:
        """Hold the worker before its next batch (testing/ops hook — lets
        backpressure be exercised deterministically)."""
        with self._cv:
            self._paused = True
            self._cv.notify_all()

    def resume(self) -> None:
        with self._cv:
            self._paused = False
            self._cv.notify_all()

    def flush(self, timeout: Optional[float] = None) -> bool:
        """Block until everything queued at call time has been answered."""
        deadline = None if timeout is None else time.monotonic() + timeout
        with self._cv:
            while self._q or self._inflight:
                rem = (None if deadline is None
                       else deadline - time.monotonic())
                if rem is not None and rem <= 0:
                    return False
                self._cv.wait(rem if rem is not None else 0.1)
        return True

    def close(self, drain: bool = True,
              timeout: Optional[float] = None) -> None:
        """Stop admission; then drain (answer pending) or abort (fail
        pending with :class:`ServeClosed`). Idempotent."""
        failed: list = []
        with self._cv:
            self._closed = True
            self._draining = drain
            if not drain:
                failed = list(self._q)
                self._q.clear()
            self._paused = False  # a paused executor must still shut down
            self._cv.notify_all()
        # fail futures OUTSIDE the lock: set_exception runs done-callbacks
        # synchronously, and a callback that re-enters close() would
        # otherwise join the worker while holding the lock the worker
        # needs to wake up and exit — deadlock
        for req in failed:
            # returns False iff the client already cancelled; otherwise it
            # moves the future to RUNNING so set_exception cannot race a
            # concurrent cancel
            if req.future.set_running_or_notify_cancel():
                req.future.set_exception(
                    ServeClosed(f"executor {self.name!r} closed "
                                "without drain"))
        # close() can be reached FROM the worker (a future done-callback
        # fires on the thread that set the result) — joining yourself
        # raises; admission is already stopped, so just skip the wait
        if threading.current_thread() is not self._worker:
            self._worker.join(timeout)

    @property
    def closed(self) -> bool:
        return self._closed

    @property
    def worker_alive(self) -> bool:
        """True while the dispatch worker thread lives (the soak harness's
        first verdict: nothing may kill it)."""
        return self._worker.is_alive()

    def stats(self) -> dict:
        """This executor's metrics snapshot + queue depth + cache stats
        (+ per-tenant admission counters once a registry exists)."""
        return self.metrics.snapshot(
            queue_depth=self.queue_depth,
            program_cache=self.program_cache.stats(),
            tenants=self.tenant_stats())

    def __enter__(self) -> "ServingExecutor":
        return self

    def __exit__(self, exc_type, exc, tb) -> None:
        self.close(drain=exc_type is None)

    # ------------------------------------------------------------------ #
    # worker                                                             #
    # ------------------------------------------------------------------ #
    def _run(self) -> None:
        cfg = self.config
        while True:
            with self._cv:
                # every state change (submit/pause/resume/close) notifies;
                # the long timeout is only a lost-wakeup safety net, and
                # keeps an abandoned (never-closed) executor nearly idle
                while not self._closed and (not self._q or self._paused):
                    self._cv.wait(1.0)
                if self._closed and not (self._draining and self._q):
                    # non-draining close already failed + cleared the queue
                    return
                first = self._q.pop(0)
                batch = [first]
                if cfg.batching and cfg.max_batch > 1:
                    t_end = time.monotonic() + cfg.max_wait_ms / 1e3
                    while len(batch) < cfg.max_batch:
                        batch.extend(self._take_matching(
                            first.group, cfg.max_batch - len(batch)))
                        if len(batch) >= cfg.max_batch:
                            break
                        rem = t_end - time.monotonic()
                        if rem <= 0 or self._closed:
                            break
                        self._cv.wait(rem)
                    # arrivals during the final wait
                    batch.extend(self._take_matching(
                        first.group, cfg.max_batch - len(batch)))
                self._inflight = len(batch)
            try:
                self._process(batch)
            except Exception as exc:
                # backstop: NOTHING may kill the worker thread — a dead
                # worker leaves every queued future unresolved forever
                # while submit() keeps admitting. Fail the batch instead.
                from ..utils import metrics as _pm

                _pm.inc("serve.worker_backstops")
                self.metrics.record_error()
                for req in batch:
                    try:
                        if not req.future.done():
                            req.future.set_exception(exc)
                    except InvalidStateError:
                        pass  # lost a race with a client cancel
            finally:
                with self._cv:
                    self._inflight = 0
                    self._cv.notify_all()

    def _take_matching(self, group, limit: int) -> list:
        """Pop up to ``limit`` queued requests of ``group`` (lock held).
        Non-matching requests keep their place — no head-of-line blocking
        across shape groups."""
        if limit <= 0:
            return []
        taken, keep = [], []
        for req in self._q:
            if len(taken) < limit and req.group == group:
                taken.append(req)
            else:
                keep.append(req)
        self._q[:] = keep
        return taken

    def _split_to_ladder(self, batch: list) -> list:
        """Greedily pack ``batch`` into chunks whose row totals the bucket
        policy accepts. A request too large even alone becomes its own
        chunk — reprocessing it routes the policy's error to its future."""
        policy = self.config.bucket_rows

        def fits(rows: int) -> bool:
            try:
                policy(rows)
                return True
            except Exception:
                return False

        chunks, cur, cur_rows = [], [], 0
        for req in batch:
            if cur and not fits(cur_rows + req.rows):
                chunks.append(cur)
                cur, cur_rows = [], 0
            cur.append(req)
            cur_rows += req.rows
        if cur:
            chunks.append(cur)
        return chunks

    def _expire(self, batch: list) -> list:
        """Drop client-cancelled and queued-past-deadline requests; returns
        the live remainder, every future moved to RUNNING — from here on a
        client ``Future.cancel()`` returns False instead of racing the
        worker's ``set_result`` (which would raise ``InvalidStateError``
        and poison the batch-mates via the backstop).

        With admission control armed, requests whose deadline cannot
        survive even one more estimated batch service time are **early
        shed** here, typed, before they consume the batch slot — the
        deadline arithmetic is one ``time.monotonic()`` clock end-to-end
        (enqueue stamp → EWMA estimate → this check)."""
        now = time.monotonic()
        adm = self._admission
        live = []
        for req in batch:
            if not req.started:
                if not req.future.set_running_or_notify_cancel():
                    continue  # cancelled while queued: never run it
                req.started = True
            if req.deadline_t is not None and now > req.deadline_t:
                self.metrics.record_deadline_expired()
                if adm is not None:
                    adm.count(req.tenant, "deadline_expired")
                req.future.set_exception(ServeDeadlineExceeded(
                    f"request expired after "
                    f"{(now - req.enq_t) * 1e3:.1f} ms in queue"))
                continue
            if req.deadline_t is not None and adm is not None:
                est = adm.estimate_service_s(req.group)
                if est is not None and now + est > req.deadline_t:
                    self.metrics.record_early_shed()
                    adm.count(req.tenant, "early_shed")
                    req.future.set_exception(ServeDeadlineExceeded(
                        f"early shed: estimated service "
                        f"{est * 1e3:.1f} ms cannot meet the deadline "
                        f"({(req.deadline_t - now) * 1e3:.1f} ms away "
                        f"after {(now - req.enq_t) * 1e3:.1f} ms queued)"))
                    continue
            live.append(req)
        return live

    def _process(self, batch: list) -> None:
        from ..utils import faults as _faults
        from ..utils import metrics as _pm

        cfg = self.config
        batch = self._expire(batch)
        if not batch:
            return
        # chaos site OUTSIDE every recovery path below: an armed
        # 'serve.worker.batch' fault escapes to the _run backstop — the
        # deterministic trigger for the "futures failed, worker alive,
        # next batch serves" contract test
        _faults.check("serve.worker.batch")
        rows = sum(r.rows for r in batch)
        feat, _ = batch[0].group
        dtype = batch[0].x.dtype
        try:
            _faults.check("serve.bucket.policy")
            bucket = cfg.bucket_rows(rows)
            over_cap = (cfg.max_bucket_bytes is not None
                        and bucket_nbytes(bucket, feat, dtype)
                        > cfg.max_bucket_bytes)
        except Exception as exc:
            # a bounded policy (FixedBuckets top size, Pow2Buckets
            # max_rows) can reject the COALESCED row count even when every
            # member request fits on its own — re-split into the largest
            # sub-batches the ladder still admits (NOT one-at-a-time:
            # sustained traffic can overflow on every cycle, and singles
            # would quietly revert to the sequential baseline).
            # A single request the policy rejects outright is a client
            # error: route it to that request's future, never the worker.
            if len(batch) > 1:
                _pm.inc("serve.bucket_splits")
                for chunk in self._split_to_ladder(batch):
                    self._process(chunk)
            else:
                self.metrics.record_error()
                batch[0].future.set_exception(exc)
            return
        if over_cap and len(batch) > 1:
            # degraded path: the coalesced bucket would blow the memory
            # cap — answer one request at a time instead
            for req in batch:
                self._process([req])
            return
        if over_cap:
            # a single over-cap request runs at (nearly) its exact shape:
            # bounded memory at the price of bucket-ladder compile reuse.
            # Sharded programs still need the batch axis to divide the
            # mesh; min_rows carries that requirement (its documented job)
            # even when multiple_of is 1 — e.g. Pow2Buckets(min_rows=4)
            # yields only multiples of 4, so the exact-shape fallback must
            # round to min_rows too, or a 1001-row request hands the
            # sharded program an indivisible batch axis.
            policy = cfg.bucket_rows
            quantum = max(int(getattr(policy, "multiple_of", 1)),
                          int(getattr(policy, "min_rows", cfg.min_rows)), 1)
            bucket = -(-rows // quantum) * quantum
            self.metrics.record_fallback_single()
        adm = self._admission
        tenants = ({r.tenant for r in batch if r.tenant is not None}
                   if adm is not None else ())
        svc_dt = [None]  # successful-dispatch duration for the estimator

        def run_once():
            t_disp = time.monotonic()
            _faults.check("serve.batch.dispatch")
            payload = np.empty((bucket,) + feat, dtype)
            off = 0
            for req in batch:
                payload[off:off + req.rows] = req.x
                off += req.rows
            if off < bucket:
                payload[off:] = 0  # zero only the pad tail, not the bucket
            prog = self.program_cache.get(
                self.model_fn, (bucket,) + feat, dtype, self.cache_token)
            out = prog(payload)
            # ONE device->host fetch per batch; per-request rows are then
            # sliced on host. Slicing the sharded device output per
            # request instead would dispatch a device program per slice —
            # more dispatches than the unbatched path it replaces.
            res = jax.tree.map(np.asarray, jax.block_until_ready(out))
            svc_dt[0] = time.monotonic() - t_disp
            return res

        try:
            out = run_once()
        except Exception:
            # HARDENED FAILURE DOMAIN (doc/robustness.md): one bounded
            # retry before failing the batch's futures — a transient
            # compile/dispatch/fetch error (OOM blip, a cache cap-clear
            # racing a compile) must not shed a whole batch that the very
            # next attempt would have served. A second failure is treated
            # as real: the futures fail typed and the worker lives on
            # (generalizing the PR 2 backstop from "don't die" to
            # "retry, then shed").
            _pm.inc("serve.batch_retries")
            try:
                out = run_once()
            except Exception as exc:
                # post-retry failure: the breaker's unit of evidence
                if adm is not None:
                    adm.on_batch_outcome(tenants, ok=False)
                self.metrics.record_error()
                for req in batch:
                    req.future.set_exception(exc)
                return
        if adm is not None:
            if svc_dt[0] is not None:
                adm.observe_service(batch[0].group, bucket, svc_dt[0])
            adm.on_batch_outcome(tenants, ok=True)
        self.metrics.record_batch(len(batch), rows, bucket)
        done_t = time.monotonic()
        off = 0
        # slices are COPIES when the request is smaller than the bucket: a
        # zero-copy view would pin the whole batch output alive for as
        # long as any client keeps its (possibly 1-row) result
        whole = len(batch) == 1 and batch[0].rows == bucket
        for req in batch:
            sl = slice(off, off + req.rows)
            res = jax.tree.map(
                lambda a, s=sl: a[s] if whole else a[s].copy(), out)
            off += req.rows
            self.metrics.record_request(done_t - req.enq_t)
            if adm is not None:
                adm.count(req.tenant, "completed")
            req.future.set_result(res)
