"""Multi-tenant admission control for the serving executor.

The overload half of ``heat_tpu.serve`` (ROADMAP open item 2): the
bounded-queue + typed-shed skeleton from PR 2 says *how many* requests may
wait — this module decides *whose* requests wait, and which ones should
never run at all. One :class:`AdmissionController` per executor owns:

* **Tenant registry** — ``register(name, priority=..., slo_ms=...,
  max_queue=..., rate_limit=...)``. Priority orders the queue (higher
  first, FIFO within a priority); ``slo_ms`` becomes the default deadline
  for the tenant's requests; ``max_queue`` is a per-tenant queue quota so
  one tenant cannot fill the shared bound; ``rate_limit`` is a token
  bucket (sustained requests/s, burst = one second's worth) shedding with
  a typed :class:`~heat_tpu.serve.errors.ServeRateLimited`.
* **Circuit breaker** (per tenant, riding the dispatch-retry machinery):
  ``breaker_failures`` consecutive *post-retry* batch-dispatch failures
  open the breaker — further requests fast-fail at admission with a typed
  :class:`~heat_tpu.serve.errors.ServeCircuitOpen` (microseconds, vs the
  milliseconds a dispatch + bounded retry burns), so a persistently
  broken program stops consuming the worker's retry budget while healthy
  tenants starve. After ``breaker_cooldown_s`` the breaker goes
  *half-open*: at most ``half_open_max`` probe requests are admitted; a
  successful dispatch closes the breaker, a failed one re-opens it. The
  probe budget self-heals after another cool-down, so probes that were
  shed before dispatch (deadline, close) cannot wedge the state machine.
  Attribution is per BATCH: every tenant with requests in a failed batch
  accumulates the failure (they share the failing program — coalescing
  is not tenant-pure, by design), and any successful dispatch for a
  tenant resets/closes; see doc/serving.md.
* **EWMA service estimator** — the worker reports each successful batch's
  dispatch duration per request group; :meth:`estimate_service_s` feeds
  the executor's *deadline-aware early shed*: a queued request whose
  deadline cannot survive even one more batch service time is dropped
  with a typed ``ServeDeadlineExceeded`` *before* it consumes a batch
  slot — under exactly the overload where wasted compute hurts most.

Everything here is host-side python state on **one clock**
(``time.monotonic``, injectable for tests): enqueue stamps, deadlines,
token refills, breaker cool-downs and service estimates all share it, so
the early-shed arithmetic (``now + estimate > deadline``) is sound by
construction — mixing in a wall clock anywhere would make it a
correctness bug (see ``tests/test_serve_admission.py``).

Thread-safety: the controller has its own lock and never takes the
executor's; the executor calls in from ``submit`` (under its condition
variable) and from the worker thread (without it) — lock order is always
executor → controller, never the reverse.

Failure domains (``doc/robustness.md``): the admission decision and the
breaker consult are fault-injection sites (``serve.admission.decide``,
``serve.breaker.probe``). Both fail *open*: a broken admission machinery
degrades that request to the legacy bounded-FIFO admission
(``serve.admission_fallbacks``), a broken breaker consult admits the
request (``serve.breaker_fallbacks``) — the dispatch path stays the
authority on health, and a bug in the new machinery can never turn into
an outage the old executor would not have had.
"""

from __future__ import annotations

import threading
import time
from dataclasses import dataclass
from typing import Callable, Dict, Optional

from ..utils import faults as _faults
from ..utils import metrics as _pm
from .errors import ServeCircuitOpen, ServeRateLimited

__all__ = ["Tenant", "AdmissionController", "DEFAULT_TENANT"]

#: the implicit tenant untagged ``submit()`` calls ride once a registry
#: exists — priority 0, no quota/rate/SLO (auto-registered on first use)
DEFAULT_TENANT = "default"

#: per-tenant counter keys, in the order tenant_stats() reports them
TENANT_COUNTERS = (
    "admitted", "completed", "shed", "rate_limited", "deadline_expired",
    "early_shed", "breaker_rejections", "breaker_opens",
    "dispatch_failures",
)


@dataclass
class Tenant:
    """One tenant's registered policy (all host-side; ``None`` = off /
    controller default)."""

    name: str
    priority: int = 0                       # higher = admitted/served first
    slo_ms: Optional[float] = None          # default per-request deadline
    max_queue: Optional[int] = None         # per-tenant queued-request quota
    rate_limit: Optional[float] = None      # sustained requests/s
    burst: Optional[float] = None           # bucket capacity; default = 1 s
    breaker_failures: Optional[int] = None      # consecutive-failure trip
    breaker_cooldown_s: Optional[float] = None  # open -> half-open delay
    half_open_max: Optional[int] = None         # probe budget per cooldown


class _TenantState:
    """Mutable per-tenant runtime state (under the controller lock)."""

    __slots__ = ("tokens", "refill_t", "breaker", "streak", "opened_t",
                 "half_open_t", "half_open_used", "counters")

    def __init__(self, tenant: Tenant, now: float):
        self.tokens = (None if tenant.rate_limit is None
                       else _bucket_burst(tenant))
        self.refill_t = now
        self.breaker = "closed"      # closed | open | half_open
        self.streak = 0              # consecutive post-retry batch failures
        self.opened_t = 0.0
        self.half_open_t = 0.0
        self.half_open_used = 0
        self.counters: Dict[str, int] = {k: 0 for k in TENANT_COUNTERS}


def _bucket_burst(tenant: Tenant) -> float:
    if tenant.burst is not None:
        return float(tenant.burst)
    return max(1.0, float(tenant.rate_limit))


class AdmissionController:
    """Tenant registry + admission state machine for one executor."""

    DEFAULT_BREAKER_FAILURES = 3
    DEFAULT_BREAKER_COOLDOWN_S = 1.0
    DEFAULT_HALF_OPEN_MAX = 2
    EWMA_ALPHA = 0.25           # service-estimator smoothing
    _MAX_GROUPS = 128           # estimator key-space bound

    def __init__(self, clock: Callable[[], float] = time.monotonic):
        self._clock = clock
        self._lock = threading.Lock()
        self._tenants: Dict[str, Tenant] = {}
        self._state: Dict[str, _TenantState] = {}
        # group -> EWMA seconds of a successful batch dispatch; keyed by
        # the request group (trailing shape + dtype — the thing that
        # decides which bucket family a batch lands in). Early shed runs
        # before the batch's bucket is computed, so finer per-bucket
        # state would have no reader.
        self._ewma: Dict = {}

    # ------------------------------------------------------------------ #
    # registry                                                           #
    # ------------------------------------------------------------------ #
    def register(self, name: str, **policy) -> Tenant:
        """Register (or re-register with new policy — ops tuning) a
        tenant. Counters and breaker state survive a re-register."""
        tenant = Tenant(name=str(name), **policy)
        if tenant.rate_limit is not None and tenant.rate_limit <= 0:
            raise ValueError(
                f"tenant {name!r}: rate_limit must be > 0, got "
                f"{tenant.rate_limit}")
        if tenant.max_queue is not None and tenant.max_queue < 1:
            raise ValueError(
                f"tenant {name!r}: max_queue must be >= 1, got "
                f"{tenant.max_queue}")
        with self._lock:
            self._tenants[tenant.name] = tenant
            st = self._state.get(tenant.name)
            if st is None:
                self._state[tenant.name] = _TenantState(tenant, self._clock())
            else:
                # policy update: re-prime the token bucket to the NEW
                # rate/burst (counters and breaker state survive)
                st.tokens = (None if tenant.rate_limit is None
                             else _bucket_burst(tenant))
                st.refill_t = self._clock()
        return tenant

    def resolve(self, name: Optional[str]) -> str:
        """Validated tenant name; ``None`` maps to the implicit
        :data:`DEFAULT_TENANT` (auto-registered, priority 0)."""
        if name is None:
            with self._lock:
                if DEFAULT_TENANT not in self._tenants:
                    t = Tenant(name=DEFAULT_TENANT)
                    self._tenants[DEFAULT_TENANT] = t
                    self._state[DEFAULT_TENANT] = _TenantState(
                        t, self._clock())
            return DEFAULT_TENANT
        name = str(name)
        if name not in self._tenants:
            raise ValueError(
                f"unknown tenant {name!r}; registered: "
                f"{sorted(self._tenants)} (register_tenant() first)")
        return name

    def get(self, name: str) -> Tenant:
        return self._tenants[name]

    def priority(self, name: str) -> int:
        return int(self._tenants[name].priority)

    def slo_ms(self, name: str) -> Optional[float]:
        return self._tenants[name].slo_ms

    @property
    def tenants(self) -> Dict[str, Tenant]:
        with self._lock:
            return dict(self._tenants)

    # ------------------------------------------------------------------ #
    # admission-time checks (called from submit, executor lock held)     #
    # ------------------------------------------------------------------ #
    def check_tenant(self, name: str, consume_token: bool = True) -> None:
        """Breaker consult (+ token bucket unless ``consume_token`` is
        False) for one incoming request. Raises the typed rejection
        (:class:`ServeCircuitOpen` / :class:`ServeRateLimited`) and ticks
        the per-tenant counter. The executor passes
        ``consume_token=False`` and takes the token LAST
        (:meth:`take_token`), after the quota check — a request shed for
        quota must not drain the bucket and misattribute later
        rejections to the rate limit."""
        now = self._clock()
        with self._lock:
            tenant = self._tenants[name]
            st = self._state[name]
            # chaos site: a broken breaker consult FAILS OPEN — the
            # request is admitted and the dispatch path stays the health
            # authority (doc/robustness.md)
            try:
                _faults.check("serve.breaker.probe")
                allowed = self._breaker_allows(tenant, st, now)
            except Exception:
                _pm.inc("serve.breaker_fallbacks")
                allowed = True
            if not allowed:
                st.counters["breaker_rejections"] += 1
                _pm.inc("serve.breaker_rejections")
                raise ServeCircuitOpen(
                    f"tenant {name!r} circuit breaker is open (recent "
                    f"batch dispatches failed persistently; probes resume "
                    f"after the "
                    f"{self._cooldown(tenant):.3g}s cool-down)")
            if consume_token:
                self._take_token(tenant, st, now)

    def take_token(self, name: str) -> None:
        """Consume one rate-limit token (no-op for unlimited tenants);
        raises the typed :class:`ServeRateLimited` when the bucket is
        empty."""
        now = self._clock()
        with self._lock:
            self._take_token(self._tenants[name], self._state[name], now)

    def refund_token(self, name: str) -> None:
        """Return a token taken for a request that was subsequently shed
        (e.g. shared queue full with no preemptible victim) — the tenant
        never got service for it, so it must not count against the rate."""
        with self._lock:
            tenant = self._tenants.get(name)
            st = self._state.get(name)
            if (tenant is None or st is None or tenant.rate_limit is None
                    or st.tokens is None):
                return
            st.tokens = min(_bucket_burst(tenant), st.tokens + 1.0)

    def _take_token(self, tenant: Tenant, st: _TenantState,
                    now: float) -> None:
        if tenant.rate_limit is None:
            return
        rate = float(tenant.rate_limit)
        burst = _bucket_burst(tenant)
        if st.tokens is None:  # policy gained a limit later
            st.tokens = burst
            st.refill_t = now
        st.tokens = min(burst, st.tokens + (now - st.refill_t) * rate)
        st.refill_t = now
        if st.tokens < 1.0:
            st.counters["rate_limited"] += 1
            raise ServeRateLimited(
                f"tenant {tenant.name!r} over its rate limit "
                f"({rate:g} req/s, burst {burst:g})")
        st.tokens -= 1.0

    def _cooldown(self, tenant: Tenant) -> float:
        return (tenant.breaker_cooldown_s
                if tenant.breaker_cooldown_s is not None
                else self.DEFAULT_BREAKER_COOLDOWN_S)

    def _breaker_allows(self, tenant: Tenant, st: _TenantState,
                        now: float) -> bool:
        if st.breaker == "closed":
            return True
        cooldown = self._cooldown(tenant)
        if st.breaker == "open":
            if now - st.opened_t < cooldown:
                return False                      # fast fail
            st.breaker = "half_open"              # cool-down elapsed
            st.half_open_t = now
            st.half_open_used = 0
        hmax = (tenant.half_open_max if tenant.half_open_max is not None
                else self.DEFAULT_HALF_OPEN_MAX)
        if now - st.half_open_t >= cooldown:
            # probes admitted earlier never produced a batch outcome
            # (shed on deadline, executor closed) — replenish the budget
            # instead of wedging half-open forever
            st.half_open_t = now
            st.half_open_used = 0
        if st.half_open_used >= hmax:
            return False
        st.half_open_used += 1
        return True

    # ------------------------------------------------------------------ #
    # dispatch outcomes (called from the worker thread, no executor lock)#
    # ------------------------------------------------------------------ #
    def on_batch_outcome(self, names, ok: bool) -> None:
        """Feed one batch's final dispatch outcome (post-retry) into the
        breaker state machine for every tenant that had requests in it."""
        now = self._clock()
        with self._lock:
            for name in names:
                tenant = self._tenants.get(name)
                st = self._state.get(name)
                if tenant is None or st is None:
                    continue
                if ok:
                    st.streak = 0
                    if st.breaker != "closed":
                        # a successful dispatch is proof of health whether
                        # it was a half-open probe or a request admitted
                        # before the breaker opened
                        st.breaker = "closed"
                    continue
                st.counters["dispatch_failures"] += 1
                st.streak += 1
                trip = (tenant.breaker_failures
                        if tenant.breaker_failures is not None
                        else self.DEFAULT_BREAKER_FAILURES)
                if st.breaker == "half_open" or st.streak >= trip:
                    if st.breaker != "open":
                        st.counters["breaker_opens"] += 1
                        _pm.inc("serve.breaker_open")
                    st.breaker = "open"
                    st.opened_t = now
                    st.streak = 0

    def observe_service(self, group, bucket: int, dt_s: float) -> None:
        """EWMA-fold one successful batch's dispatch duration (``bucket``
        rides along for callers' logging; the estimate is per group)."""
        with self._lock:
            if len(self._ewma) > self._MAX_GROUPS:
                self._ewma.clear()
            a = self.EWMA_ALPHA
            prev = self._ewma.get(group)
            self._ewma[group] = (dt_s if prev is None
                                 else (1 - a) * prev + a * dt_s)

    def estimate_service_s(self, group) -> Optional[float]:
        """EWMA batch service time for ``group`` (None until observed) —
        the early-shed bound: a queued request whose ``now + estimate``
        exceeds its deadline provably cannot meet it."""
        with self._lock:
            return self._ewma.get(group)

    # ------------------------------------------------------------------ #
    # accounting / introspection                                         #
    # ------------------------------------------------------------------ #
    def count(self, name: Optional[str], key: str, n: int = 1) -> None:
        if name is None:
            return
        with self._lock:
            st = self._state.get(name)
            if st is not None:
                st.counters[key] += n

    def breaker_state(self, name: str) -> str:
        with self._lock:
            st = self._state.get(name)
            return st.breaker if st is not None else "closed"

    def tenant_stats(self) -> dict:
        """JSON-ready per-tenant snapshot: policy + counters + breaker."""
        with self._lock:
            out = {}
            for name, tenant in self._tenants.items():
                st = self._state[name]
                out[name] = {
                    "priority": int(tenant.priority),
                    "slo_ms": tenant.slo_ms,
                    "max_queue": tenant.max_queue,
                    "rate_limit": tenant.rate_limit,
                    "breaker": st.breaker,
                    **{k: int(v) for k, v in st.counters.items()},
                }
            return out
