"""Continuous-batching decode engine: slot-based device-resident KV cache
with in-flight request join/leave (ROADMAP item 2(d) — the LLM-serving
traffic shape).

``TransformerLM.generate()`` is a monolithic batch program: every sequence
in a batch runs until the longest finishes, and new requests wait for the
whole batch to drain (the convoy effect). The :class:`DecodeEngine`
replaces that for serving traffic with SLOTS independent lanes over a
persistent, device-resident KV cache:

* **State.** ``(n_layers, SLOTS, S_cap, H, Dh)`` K/V lanes sharded over
  the model's dp×tp grid (slots over dp, heads over tp), plus per-slot
  position and last-token vectors — all device-resident for the engine's
  lifetime. ``S_cap`` is a rung of the power-of-two sequence ladder
  (``TransformerLM.prompt_bucket``), and every prompt pads onto the same
  ladder, so the compiled-program set is finite by construction.
* **Exactly TWO executables per (bucket, codec) signature.** A bucketed
  PREFILL program (runs the padded prompt forward, writes its K/V into a
  free slot, samples the first token) and ONE donated-carry DECODE-STEP
  program (cache, positions, live-mask, tokens in; cache donated back)
  dispatched repeatedly. Steady-state decoding compiles nothing, and the
  only per-step device→host transfer is the sampled-token vector
  (SLOTS·int32) — cache, positions and logits never leave the device
  (audited via ``jax.transfer_guard`` in ``tests/test_serve_decode.py``).
* **Join/leave is masked, not specialized.** A finished slot (EOS or
  max_new_tokens) resolves its future and goes dead in the live-mask; a
  queued request prefills into the free slot between steps. The ONE step
  executable serves every occupancy — it never re-specializes.
* **Program keys carry the wire-codec configuration.** Like every other
  builder cache, prefill/step programs key on ``fusion.quant_key() /
  chunk_key() / hier_key()`` — the per-token tp psums ride
  :func:`heat_tpu.core.fusion.packed_psum`, so codec toggles compile
  SIBLING programs, toggle-back re-hits, and steady-state misses stay 0.
* **Tenancy.** ``register_tenant`` arms the same
  :class:`~heat_tpu.serve.admission.AdmissionController` registry the
  batch executor uses: slot grants are priority-ordered (FIFO within a
  priority), tenant ``slo_ms`` is the default deadline, and per-tenant
  admitted/completed/shed counters fold into ``runtime_stats()``.
* **Fault containment.** A failed decode-step dispatch degrades that
  step to the eager per-slot path (plain global-array jnp ops, one slot
  at a time) with every future intact — ``serve.decode_fallbacks`` ticks
  and the chaos matrix pins fault-free-equal tokens
  (``serve.decode.step`` in ``doc/robustness.md``).

``serve_transformer(model, params, seq_len, decode=True)`` is the adapter
entry point; ``examples/nn/gpt_parallel.py --serve`` drives it.
"""

from __future__ import annotations

import itertools
import threading
import time
import weakref
from collections import deque
from concurrent.futures import Future, InvalidStateError
from dataclasses import dataclass
from typing import List, Optional

import numpy as np

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import NamedSharding, PartitionSpec as P

from ..core._compat import shard_map
from .errors import ServeClosed, ServeDeadlineExceeded, ServeOverloaded
from .program_cache import ProgramCache

__all__ = ["DecodeConfig", "DecodeEngine", "live_decode_engines",
           "DECODE_STATS_KEYS"]

# the pinned runtime_stats()["serve"]["decode"] shape (tests/test_stats_contract.py)
DECODE_STATS_KEYS = ("slots", "occupancy", "prefills", "decode_steps",
                     "tokens_out", "decode_fallbacks")

_ENGINES: "weakref.WeakSet[DecodeEngine]" = weakref.WeakSet()


def live_decode_engines():
    return list(_ENGINES)


@dataclass
class DecodeConfig:
    """Engine policy knobs (host-side; none affect greedy results)."""

    slots: Optional[int] = None     # default 2 * dp_world, rounded up
    max_seq_len: int = 256          # S_cap = prompt_bucket(max_seq_len)
    queue_limit: int = 128          # admission bound -> ServeOverloaded
    default_deadline_ms: Optional[float] = None
    temperature: float = 0.0        # 0 = greedy (the parity-checked mode)
    seed: int = 0                   # sampling stream (temperature > 0)

    def __post_init__(self):
        if self.queue_limit < 1:
            raise ValueError(
                f"queue_limit must be >= 1, got {self.queue_limit}")
        if self.max_seq_len < 2:
            raise ValueError(
                f"max_seq_len must be >= 2, got {self.max_seq_len}")


_SEQ = itertools.count()  # FIFO tiebreaker within a priority


class _DecodeRequest:
    __slots__ = ("prompt", "max_new", "eos_id", "tenant", "priority", "seq",
                 "enq_t", "deadline_t", "future", "generated", "slot")

    def __init__(self, prompt: np.ndarray, max_new: int,
                 eos_id: Optional[int], deadline_t: Optional[float],
                 tenant: Optional[str]):
        self.prompt = prompt
        self.max_new = max_new
        self.eos_id = eos_id
        self.tenant = tenant
        self.priority = 0
        self.seq = next(_SEQ)
        self.enq_t = time.monotonic()
        self.deadline_t = deadline_t
        self.future = Future()
        self.generated: List[int] = []
        self.slot = -1


class DecodeEngine:
    """Continuous-batching decode front end for one ``TransformerLM``.

    Parameters
    ----------
    model : TransformerLM
        A pp=1, sp=1 dense-MLP model (``check_decode_grid``) — any dp×tp
        grid, optionally with the leading dcn tier axis.
    params : pytree
        The model's sharded parameters (``model.init`` / ``shard_params``).
    config : DecodeConfig, optional
    program_cache : ProgramCache, optional
        Counters aggregate under ``serve.program_*`` like every serving
        cache; pass a shared one to pool programs across engines.

    Always ``close()`` an engine you are done with (or use it as a
    context manager) — the worker thread holds a reference.
    """

    def __init__(self, model, params, config: Optional[DecodeConfig] = None,
                 *, name: str = "decode",
                 program_cache: Optional[ProgramCache] = None):
        model.check_decode_grid()
        self.model = model
        self.params = params
        self.config = config if config is not None else DecodeConfig()
        self.name = name
        self.program_cache = (program_cache if program_cache is not None
                              else ProgramCache(name=name))
        dpw = model.dp_world
        slots = self.config.slots
        if slots is None:
            slots = 2 * dpw
        # slots shard over the data-parallel world: round up to divide
        self.slots = -(-int(slots) // dpw) * dpw
        self.S_cap = model.prompt_bucket(self.config.max_seq_len)
        c = model.cfg
        if c.vocab < 2:
            raise ValueError("decode needs vocab >= 2")
        self._dp_axes = (("dcn", "dp") if model._has_dcn else "dp")
        mesh = model.grid.mesh
        self._cache_spec = P(None, self._dp_axes, None, "tp", None)
        self._vec_spec = P(self._dp_axes)
        cache_sh = NamedSharding(mesh, self._cache_spec)
        vec_sh = NamedSharding(mesh, self._vec_spec)
        Hs = c.n_heads  # global head axis; tp shards it via the sharding
        shape = (c.n_layers, self.slots, self.S_cap, Hs, c.head_dim)
        self._ck = jax.device_put(jnp.zeros(shape, c.compute_dtype), cache_sh)
        self._cv = jax.device_put(jnp.zeros(shape, c.compute_dtype), cache_sh)
        self._pos = jax.device_put(jnp.zeros(self.slots, jnp.int32), vec_sh)
        self._toks = jax.device_put(jnp.zeros(self.slots, jnp.int32), vec_sh)
        self._base_key = jax.random.key(self.config.seed)
        # host mirrors: which request owns each slot (None = free) and the
        # live mask uploaded to the step program every dispatch
        self._slot_req: List[Optional[_DecodeRequest]] = [None] * self.slots
        self._live = np.zeros(self.slots, bool)
        # device-resident live mask, re-uploaded ONLY on join/leave (a
        # steady full-occupancy decode stream uploads nothing per step)
        self._live_dev = None
        self._greedy_key = None  # cached key: greedy ignores it, so one
        #                          constant array serves every dispatch
        self._q: List[_DecodeRequest] = []
        self._cv_lock = threading.Condition()
        self._admission = None
        self._closed = False
        self._draining = False
        self._paused = False
        self._step_seq = 0
        self._prefill_seq = 0
        # per-engine figures (process-wide serve.decode_* counters mirror)
        self._prefills = 0
        self._steps = 0
        self._tokens_out = 0
        self._fallbacks = 0
        self._occupancy = deque(maxlen=512)
        self._worker = threading.Thread(
            target=self._run, name=f"heat-decode-{name}", daemon=True)
        self._worker.start()
        _ENGINES.add(self)

    # ------------------------------------------------------------------ #
    # submission / tenancy                                               #
    # ------------------------------------------------------------------ #
    def submit(self, prompt, max_new_tokens: int,
               eos_id: Optional[int] = None,
               deadline_ms: Optional[float] = None,
               tenant: Optional[str] = None) -> Future:
        """Enqueue one decode request; returns a Future resolving to the
        full int32 token sequence (prompt + generated — the
        ``generate()`` contract per request). Generation stops at
        ``max_new_tokens`` or on sampling ``eos_id`` (included in the
        result). Raises the typed serve errors on shed/close."""
        prompt = np.asarray(prompt, np.int32).reshape(-1)
        if prompt.size < 1:
            raise ValueError("prompt must have at least one token")
        if (prompt < 0).any() or (prompt >= self.model.cfg.vocab).any():
            raise ValueError("prompt tokens outside the model vocab")
        max_new = int(max_new_tokens)
        if max_new < 1:
            raise ValueError(f"max_new_tokens must be >= 1, got {max_new}")
        need = self.model.prompt_bucket(prompt.size) + max_new
        if need > self.S_cap:
            raise ValueError(
                f"request needs {need} cache rows (prompt bucket "
                f"{self.model.prompt_bucket(prompt.size)} + {max_new} new) "
                f"but the engine's sequence bucket is {self.S_cap}; raise "
                f"DecodeConfig.max_seq_len")
        adm = self._admission
        if adm is not None:
            tname = adm.resolve(tenant)
        elif tenant is not None:
            raise ValueError(
                f"submit(tenant={tenant!r}) needs register_tenant() first")
        else:
            tname = None
        if deadline_ms is None:
            deadline_ms = self.config.default_deadline_ms
            if deadline_ms is None and adm is not None:
                deadline_ms = adm.slo_ms(tname)
        deadline_t = (None if deadline_ms is None
                      else time.monotonic() + deadline_ms / 1e3)
        req = _DecodeRequest(prompt, max_new, eos_id, deadline_t, tname)
        with self._cv_lock:
            if self._closed:
                raise ServeClosed(f"decode engine {self.name!r} is closed")
            if len(self._q) >= self.config.queue_limit:
                if adm is not None:
                    adm.count(tname, "shed")
                from ..utils import metrics as _pm

                _pm.inc("serve.decode_shed")
                raise ServeOverloaded(
                    f"decode engine {self.name!r} queue is full "
                    f"({self.config.queue_limit} pending)")
            if adm is not None:
                req.priority = int(adm.get(tname).priority)
                adm.count(tname, "admitted")
            self._insert(req)
            self._cv_lock.notify_all()
        return req.future

    def generate(self, prompt, max_new_tokens: int,
                 eos_id: Optional[int] = None,
                 timeout: Optional[float] = None) -> np.ndarray:
        """Synchronous convenience: ``submit(...).result(timeout)``."""
        return self.submit(prompt, max_new_tokens,
                           eos_id=eos_id).result(timeout)

    def register_tenant(self, name: str, *, priority: int = 0,
                        slo_ms: Optional[float] = None, **policy):
        """Register a tenant — the same
        :class:`~heat_tpu.serve.admission.AdmissionController` registry
        the batch executor arms. Slot grants become priority-ordered
        (higher priority prefills first when a slot frees; FIFO within a
        priority) and ``slo_ms`` is the tenant's default deadline. The
        rate/breaker knobs are accepted for registry parity but decode
        admission enforces only priority/SLO/queue bound (documented in
        ``doc/serving.md``)."""
        from .admission import AdmissionController

        with self._cv_lock:
            if self._admission is None:
                self._admission = AdmissionController()
            adm = self._admission
        return adm.register(name, priority=priority, slo_ms=slo_ms, **policy)

    @property
    def admission(self):
        return self._admission

    def _insert(self, req: _DecodeRequest) -> None:
        """Priority-ordered insert (lock held): descending priority, FIFO
        within one — identical discipline to the batch executor."""
        q = self._q
        key = (-req.priority, req.seq)
        i = len(q)
        while i > 0 and (-q[i - 1].priority, q[i - 1].seq) > key:
            i -= 1
        q.insert(i, req)

    # ------------------------------------------------------------------ #
    # lifecycle / introspection                                          #
    # ------------------------------------------------------------------ #
    @property
    def live_slots(self) -> int:
        return int(self._live.sum())

    @property
    def queue_depth(self) -> int:
        with self._cv_lock:
            return len(self._q) + self.live_slots

    @property
    def worker_alive(self) -> bool:
        return self._worker.is_alive()

    def pause(self) -> None:
        """Hold the worker before its next admit/step (test/ops hook)."""
        with self._cv_lock:
            self._paused = True
            self._cv_lock.notify_all()

    def resume(self) -> None:
        with self._cv_lock:
            self._paused = False
            self._cv_lock.notify_all()

    def flush(self, timeout: Optional[float] = None) -> bool:
        """Block until everything queued/live at call time is answered."""
        deadline = None if timeout is None else time.monotonic() + timeout
        with self._cv_lock:
            while self._q or self._live.any():
                rem = (None if deadline is None
                       else deadline - time.monotonic())
                if rem is not None and rem <= 0:
                    return False
                self._cv_lock.wait(rem if rem is not None else 0.1)
        return True

    def close(self, drain: bool = True,
              timeout: Optional[float] = None) -> None:
        """Stop admission; drain (finish queued + live sequences) or
        abort (fail them with :class:`ServeClosed`). Idempotent."""
        queued: list = []
        inflight: list = []
        with self._cv_lock:
            self._closed = True
            self._draining = drain
            if not drain:
                queued = list(self._q)
                self._q.clear()
                for s, req in enumerate(self._slot_req):
                    if req is not None:
                        inflight.append(req)
                        self._slot_req[s] = None
                self._live[:] = False
                self._live_dev = None
            self._paused = False
            self._cv_lock.notify_all()
        # fail futures OUTSIDE the lock (done-callback discipline). Queued
        # futures are PENDING: claim them so a client cancel cannot race
        # set_exception. Slot-granted futures are already RUNNING (claimed
        # at grant) — set_running_or_notify_cancel would RAISE on them, so
        # they take the done()-guarded path like _reset_state, tolerating
        # a race with the worker resolving its last step.
        err = ServeClosed(
            f"decode engine {self.name!r} closed without drain")
        for req in queued:
            if req.future.set_running_or_notify_cancel():
                req.future.set_exception(err)
        for req in inflight:
            try:
                if not req.future.done():
                    req.future.set_exception(err)
            except InvalidStateError:
                pass  # the worker's final step resolved it first
        if threading.current_thread() is not self._worker:
            self._worker.join(timeout)

    @property
    def closed(self) -> bool:
        return self._closed

    def __enter__(self) -> "DecodeEngine":
        return self

    def __exit__(self, exc_type, exc, tb) -> None:
        self.close(drain=exc_type is None)

    def stats(self) -> dict:
        """Engine snapshot: the pinned decode figures plus queue/cache/
        tenant detail."""
        occ = list(self._occupancy)
        adm = self._admission
        return {
            "slots": self.slots,
            "live": self.live_slots,
            "queue_depth": len(self._q),
            "seq_bucket": self.S_cap,
            "occupancy": (sum(occ) / len(occ)) if occ else 0.0,
            "prefills": self._prefills,
            "decode_steps": self._steps,
            "tokens_out": self._tokens_out,
            "decode_fallbacks": self._fallbacks,
            "program_cache": self.program_cache.stats(),
            "tenants": adm.tenant_stats() if adm is not None else {},
        }

    def warmup(self, prompt_lens=None) -> dict:
        """Pre-compile the prefill ladder + the decode step so traffic
        never pays a compile: one throwaway prefill per distinct prompt
        bucket (into slot 0, never marked live — the next real prefill
        overwrites it) and one all-dead decode step. Returns the program
        cache stats; steady-state traffic over the same ladder must add
        zero misses from here on. Must run before traffic: the
        throwaway prefill writes slot 0's cache rows."""
        with self._cv_lock:
            if self._q or self._live.any():
                raise RuntimeError(
                    "warmup() must run before traffic (its throwaway "
                    "prefill writes slot 0)")
        if prompt_lens is None:
            rungs, r = [], self.model.PROMPT_BUCKET_MIN
            while r < self.S_cap:
                rungs.append(r)
                r <<= 1
            prompt_lens = rungs
        seen = set()
        for s0 in prompt_lens:
            sp = self.model.prompt_bucket(int(s0))
            if sp in seen or sp >= self.S_cap:
                continue
            seen.add(sp)
            self._dispatch_prefill(np.zeros(int(s0), np.int32), 0,
                                   record=False)
        self._dispatch_step(np.zeros(self.slots, bool), record=False)
        return self.program_cache.stats()

    # ------------------------------------------------------------------ #
    # compiled programs                                                  #
    # ------------------------------------------------------------------ #
    def _wire(self):
        """The (quant, chunk, hier) key triple captured at BUILD time and
        pinned into the traced body — jax traces at first dispatch, and a
        codec toggle in between must not change the wire format out from
        under the program key (the PR 9 r4 lesson)."""
        from ..core import fusion

        return (fusion.quant_key(), fusion.chunk_key(), fusion.hier_key())

    def _dp_index(self):
        m = self.model
        idx = lax.axis_index("dp")
        if m._has_dcn:
            idx = lax.axis_index("dcn") * m.dp + idx
        return idx

    def _step_prog(self):
        """THE decode-step executable: (params, ck, cv, pos, live, toks,
        key) -> (ck, cv, pos', toks'), carries donated. One per
        (S_cap, slots, temperature, codec-keys) signature."""
        wire = self._wire()
        temp = float(self.config.temperature)
        key = ("decode_step", self.S_cap, self.slots, temp) + wire

        def build():
            m, c = self.model, self.model.cfg

            def body(params, ck, cv, pos, live, toks, skey):
                Bl = toks.shape[0]
                dtype = c.compute_dtype
                stage_params = jax.tree.map(lambda a: a[0],
                                            params["stages"])
                x = params["embed"][toks].astype(dtype)[:, None, :]
                new_k, new_v = ck, cv
                for l in range(c.n_layers):
                    p_l = m._cast_params(
                        jax.tree.map(lambda a: a[l], stage_params))
                    x, ckl, cvl = m._cache_layer_step(
                        p_l, x, new_k[l], new_v[l], pos, wire=wire)
                    new_k = new_k.at[l].set(ckl)
                    new_v = new_v.at[l].set(cvl)
                logits = m._head(params, x)[:, 0]
                if temp == 0.0:
                    nxt = jnp.argmax(logits, axis=-1).astype(jnp.int32)
                else:
                    gsl = self._dp_index() * Bl + jnp.arange(Bl)
                    keys = jax.vmap(
                        lambda i: jax.random.fold_in(skey, i))(gsl)
                    nxt = jax.vmap(lambda k, lg: jax.random.categorical(
                        k, lg / temp))(keys, logits).astype(jnp.int32)
                # join/leave is a MASK, not a program change: dead slots
                # keep their token and position (their cache write lands
                # on the same already-masked row every step)
                toks2 = jnp.where(live, nxt, toks)
                pos2 = pos + live.astype(jnp.int32)
                return new_k, new_v, pos2, toks2

            cs, vs = self._cache_spec, self._vec_spec
            sm = shard_map(
                body, mesh=self.model.grid.mesh,
                in_specs=(self.model.param_specs(), cs, cs, vs, vs, vs,
                          P()),
                out_specs=(cs, cs, vs, vs), check_vma=False)
            return jax.jit(sm, donate_argnums=(1, 2, 3, 5))

        return self.program_cache.get_custom(key, build)

    def _prefill_prog(self, Sp: int):
        """The bucketed prefill executable for prompt bucket ``Sp``:
        (params, ck, cv, pos, toks, prompt, n_valid, slot, key) ->
        (ck, cv, pos', toks', first_token); carries donated.

        The prompt rides replicated (every dp shard runs the forward,
        only the owning shard keeps the K/V write) and joins dispatch
        one request at a time — dp-way redundant prefill compute and k
        serialized dispatches on a k-request join. Acceptable while
        prefill is a small fraction of decode wall (the benched shape);
        the batched form (one prompt row per dp shard, one dispatch per
        wave of grants) is the known follow-up when prefill-bound."""
        wire = self._wire()
        temp = float(self.config.temperature)
        key = ("decode_prefill", Sp, self.S_cap, self.slots, temp) + wire

        def build():
            m = self.model

            def body(params, ck, cv, pos, toks, prompt, n_valid, slot,
                     skey):
                ks, vs, logits = m._prompt_kv_logits(
                    params, prompt[None], n_valid, wire=wire)
                if temp == 0.0:
                    first = jnp.argmax(logits[0]).astype(jnp.int32)
                else:
                    first = jax.random.categorical(
                        jax.random.fold_in(skey, slot),
                        logits[0] / temp).astype(jnp.int32)
                ls = ck.shape[1]  # local slots on this dp shard
                local = slot - self._dp_index() * ls
                ok = (local >= 0) & (local < ls)
                lc = jnp.clip(local, 0, ls - 1)
                # non-owning dp shards write the slot's OWN current rows
                # back (a no-op): the select is block-sized, never a
                # full-cache copy — prefill cost stays O(prompt), not
                # O(cache)
                for l in range(m.cfg.n_layers):
                    idx = (jnp.int32(l), lc, jnp.int32(0), jnp.int32(0),
                           jnp.int32(0))
                    for buf_i, new in ((0, ks[l]), (1, vs[l])):
                        buf = (ck, cv)[buf_i]
                        cur = lax.dynamic_slice(
                            buf, idx, (1, 1) + new.shape[1:])
                        upd = jnp.where(ok, new[None].astype(buf.dtype),
                                        cur)
                        buf = lax.dynamic_update_slice(buf, upd, idx)
                        if buf_i == 0:
                            ck = buf
                        else:
                            cv = buf
                hit = ok & (jnp.arange(ls) == lc)
                pos = jnp.where(hit, n_valid, pos)
                toks = jnp.where(hit, first, toks)
                return ck, cv, pos, toks, first

            cs, vs = self._cache_spec, self._vec_spec
            sm = shard_map(
                body, mesh=self.model.grid.mesh,
                in_specs=(self.model.param_specs(), cs, cs, vs, vs, P(),
                          P(), P(), P()),
                out_specs=(cs, cs, vs, vs, P()), check_vma=False)
            return jax.jit(sm, donate_argnums=(1, 2, 3, 4))

        return self.program_cache.get_custom(key, build)

    # ------------------------------------------------------------------ #
    # the device-residency choke point                                   #
    # ------------------------------------------------------------------ #
    @staticmethod
    def _fetch(arr) -> np.ndarray:
        """The ONE device→host doorway. Everything else the worker does
        stays on device, so a test wrapping the engine in
        ``jax.transfer_guard_device_to_host("disallow")`` proves the
        per-step fetch is only the sampled-token vector."""
        with jax.transfer_guard_device_to_host("allow"):
            return np.asarray(arr)

    # ------------------------------------------------------------------ #
    # worker                                                             #
    # ------------------------------------------------------------------ #
    def _run(self) -> None:
        from ..utils import metrics as _pm

        while True:
            expired: list = []
            grants: list = []
            with self._cv_lock:
                while not self._closed and (
                        self._paused
                        or (not self._q and not self._live.any())):
                    self._cv_lock.wait(1.0)
                if self._closed and not (
                        self._draining
                        and (self._q or self._live.any())):
                    return
                if not self._paused:
                    grants, expired = self._grant_locked()
            for req in expired:
                self._fail_deadline(req)
            try:
                for req, slot in grants:
                    self._do_prefill(req, slot)
                if self._live.any():
                    self._do_step()
            except Exception as exc:
                # backstop: NOTHING kills the worker. The donated device
                # state may be gone — fail every in-flight future typed,
                # free the slots, and rebuild fresh lanes.
                _pm.inc("serve.worker_backstops")
                self._reset_state(exc)
            finally:
                with self._cv_lock:
                    self._cv_lock.notify_all()

    def _grant_locked(self):
        """Pop (request, slot) grants for every free slot while the queue
        has work (lock held); queued-past-deadline and client-cancelled
        requests drop out. The queue is priority-ordered at insert, so
        grants ARE the tenant-priority order."""
        grants, expired = [], []
        now = time.monotonic()
        free = [s for s in range(self.slots) if self._slot_req[s] is None]
        while free and self._q:
            req = self._q.pop(0)
            if not req.future.set_running_or_notify_cancel():
                continue  # cancelled while queued: never run it
            if req.deadline_t is not None and now > req.deadline_t:
                expired.append(req)
                continue
            slot = free.pop(0)
            req.slot = slot
            self._slot_req[slot] = req
            grants.append((req, slot))
        return grants, expired

    def _fail_deadline(self, req) -> None:
        from ..utils import metrics as _pm

        _pm.inc("serve.decode_deadline_expired")
        if self._admission is not None:
            self._admission.count(req.tenant, "deadline_expired")
        req.future.set_exception(ServeDeadlineExceeded(
            f"decode request expired after "
            f"{(time.monotonic() - req.enq_t) * 1e3:.1f} ms in queue"))

    def _next_key(self, salt: int):
        return jax.random.fold_in(self._base_key, salt)

    def _dispatch_prefill(self, prompt: np.ndarray, slot: int,
                          record: bool = True):
        from ..utils import metrics as _pm

        m = self.model
        S0 = int(prompt.size)
        Sp = m.prompt_bucket(S0)
        prog = self._prefill_prog(Sp)
        padded = np.zeros(Sp, np.int32)
        padded[:S0] = prompt
        self._prefill_seq += 1
        out = prog(self.params, self._ck, self._cv, self._pos, self._toks,
                   jnp.asarray(padded), jnp.int32(S0), jnp.int32(slot),
                   self._next_key(2 * self._prefill_seq + 1))
        self._ck, self._cv, self._pos, self._toks, first = out
        if record:
            self._prefills += 1
            _pm.inc("serve.decode_prefills")
        return int(self._fetch(first))

    def _do_prefill(self, req: _DecodeRequest, slot: int) -> None:
        from ..utils import metrics as _pm

        try:
            first = self._dispatch_prefill(req.prompt, slot)
        except Exception as exc:
            # a failed prefill fails ITS request only; the slot stays
            # free and the engine (and every other lane) lives on
            if self._donated_gone():
                raise  # state lost mid-donation: the backstop rebuilds
            self._slot_req[slot] = None
            req.future.set_exception(exc)
            return
        req.generated = [first]
        self._tokens_out += 1
        _pm.inc("serve.decode_tokens_out")
        if req.max_new <= 1 or (req.eos_id is not None
                                and first == req.eos_id):
            self._finish(slot, req)
        else:
            self._live[slot] = True
            self._live_dev = None  # membership changed: re-upload

    def _dispatch_step(self, live: np.ndarray, record: bool = True):
        from ..utils import faults as _faults
        from ..utils import metrics as _pm

        self._step_seq += 1
        prog = self._step_prog()
        if float(self.config.temperature) == 0.0:
            # greedy ignores the key: one cached constant avoids a
            # fold_in dispatch on every step of the hot loop
            if self._greedy_key is None:
                self._greedy_key = self._base_key
            skey = self._greedy_key
        else:
            skey = self._next_key(2 * self._step_seq)
        if self._live_dev is None:
            self._live_dev = jax.device_put(
                live, NamedSharding(self.model.grid.mesh, self._vec_spec))
        try:
            _faults.check("serve.decode.step")
            out = prog(self.params, self._ck, self._cv, self._pos,
                       self._live_dev, self._toks, skey)
        except Exception:
            if self._donated_gone():
                raise  # donated buffers invalidated mid-dispatch (PR 8)
            # DEGRADED: the eager per-slot path — same mathematics, one
            # slot at a time in plain global-array ops, futures intact
            _pm.inc("serve.decode_fallbacks")
            self._fallbacks += 1
            out = self._step_eager(live, skey)
        self._ck, self._cv, self._pos, toks2 = out
        self._toks = toks2
        if record:
            self._steps += 1
            _pm.inc("serve.decode_steps")
        return self._fetch(toks2)

    def _do_step(self) -> None:
        from ..utils import metrics as _pm

        live = self._live.copy()
        n_live = int(live.sum())
        toks_np = self._dispatch_step(live)
        self._occupancy.append(n_live / self.slots)
        self._tokens_out += n_live
        _pm.inc("serve.decode_tokens_out", n_live)
        for slot in np.nonzero(live)[0]:
            req = self._slot_req[slot]
            if req is None:
                continue
            t = int(toks_np[slot])
            req.generated.append(t)
            done = (len(req.generated) >= req.max_new
                    or (req.eos_id is not None and t == req.eos_id))
            if done:
                self._finish(slot, req)

    def _finish(self, slot: int, req: _DecodeRequest) -> None:
        from ..utils import metrics as _pm

        self._live[slot] = False
        self._live_dev = None  # membership changed: re-upload
        self._slot_req[slot] = None
        _pm.inc("serve.decode_completed")
        if self._admission is not None:
            self._admission.count(req.tenant, "completed")
        req.future.set_result(np.concatenate(
            [req.prompt, np.asarray(req.generated, np.int32)]))

    def _donated_gone(self) -> bool:
        try:
            return bool(self._ck.is_deleted())
        except Exception:
            return False

    def _reset_state(self, exc: Exception) -> None:
        """Backstop recovery: fail every in-flight future typed, free all
        slots, rebuild fresh device lanes (the donated ones may be
        invalid)."""
        c = self.model.cfg
        mesh = self.model.grid.mesh
        cache_sh = NamedSharding(mesh, self._cache_spec)
        vec_sh = NamedSharding(mesh, self._vec_spec)
        shape = (c.n_layers, self.slots, self.S_cap, c.n_heads, c.head_dim)
        self._ck = jax.device_put(jnp.zeros(shape, c.compute_dtype),
                                  cache_sh)
        self._cv = jax.device_put(jnp.zeros(shape, c.compute_dtype),
                                  cache_sh)
        self._pos = jax.device_put(jnp.zeros(self.slots, jnp.int32), vec_sh)
        self._toks = jax.device_put(jnp.zeros(self.slots, jnp.int32),
                                    vec_sh)
        failed = []
        with self._cv_lock:
            for s, req in enumerate(self._slot_req):
                if req is not None:
                    failed.append(req)
                    self._slot_req[s] = None
            self._live[:] = False
            self._live_dev = None
        for req in failed:
            try:
                if not req.future.done():
                    req.future.set_exception(exc)
            except Exception:
                pass

    # ------------------------------------------------------------------ #
    # the eager per-slot degraded path                                   #
    # ------------------------------------------------------------------ #
    def _step_eager(self, live: np.ndarray, skey):
        """One decode step as plain per-slot global-array jnp ops — no
        compiled step executable involved. Slow (one slot at a time,
        GSPMD per-op dispatch) but it keeps every future intact when the
        step dispatch fails; values match the compiled step (same masked
        attention over the same cache rows). Host-known per-slot
        positions/tokens drive it, so shapes stay static."""
        from ..nn.transformer import _rmsnorm, rope_apply

        m, c = self.model, self.model.cfg
        params = self.params
        dtype = c.compute_dtype
        stage_params = jax.tree.map(lambda a: a[0], params["stages"])
        pos_h = self._fetch(self._pos)
        toks_h = self._fetch(self._toks)
        ck, cv = self._ck, self._cv
        new_toks = toks_h.copy()
        for s in np.nonzero(live)[0]:
            s = int(s)
            p = jnp.int32(int(pos_h[s]))
            x = params["embed"][int(toks_h[s])].astype(dtype)[None, None, :]
            for l in range(c.n_layers):
                p_l = m._cast_params(
                    jax.tree.map(lambda a: a[l], stage_params))
                a_in = _rmsnorm(x, p_l["ln1"])
                qkv = jnp.einsum("bsd,dohk->bsohk", a_in, p_l["wqkv"])
                q, k, v = qkv[:, :, 0], qkv[:, :, 1], qkv[:, :, 2]
                if c.rope:
                    q = rope_apply(q, p[None], c.rope_theta)
                    k = rope_apply(k, p[None], c.rope_theta)
                ck = ck.at[l, s, p].set(k[0, 0].astype(ck.dtype))
                cv = cv.at[l, s, p].set(v[0, 0].astype(cv.dtype))
                attn = m._attn_from_cache(q, ck[l, s][None], cv[l, s][None],
                                          p + 1)
                x = x + jnp.einsum("bshk,hkd->bsd", attn, p_l["wproj"])
                m_in = _rmsnorm(x, p_l["ln2"])
                x = x + jax.nn.gelu(m_in @ p_l["w_up"]) @ p_l["w_down"]
            logits = m._head(params, x)[0, 0]
            temp = float(self.config.temperature)
            if temp == 0.0:
                nxt = int(self._fetch(jnp.argmax(logits)))
            else:
                nxt = int(self._fetch(jax.random.categorical(
                    jax.random.fold_in(skey, s), logits / temp)))
            new_toks[s] = nxt
        pos2 = jax.device_put(
            pos_h + live.astype(np.int32),
            NamedSharding(self.model.grid.mesh, self._vec_spec))
        toks2 = jax.device_put(
            new_toks,
            NamedSharding(self.model.grid.mesh, self._vec_spec))
        return ck, cv, pos2, toks2
