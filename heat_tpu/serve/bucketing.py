"""Shape-bucket policies: map a batch's row count onto a small closed set
of padded shapes.

XLA compiles one executable per input shape; admitting raw request shapes
would compile (and cache) an executable per distinct row count — unbounded
compile latency in the serving path. A bucket policy quantizes the batch's
row count to a finite ladder (powers of two by default), so after one
warmup pass over the ladder, steady-state traffic of ANY row mix reuses
the same few compiled programs: zero recompiles (asserted by the
program-cache counters, ``tests/test_serve.py``).

A policy is any callable ``rows -> bucket_rows`` with ``bucket_rows >=
rows``; pass one via ``ServeConfig.bucket_rows`` to override the default.
A policy MAY additionally expose the attributes the executor probes:

* ``min_rows`` — the mesh-divisibility floor (every bucket is a multiple
  of it). Drives the default ``warmup()`` coverage and the divisibility
  of the over-cap exact-shape fallback; absent, the executor falls back
  to ``ServeConfig.min_rows``.
* ``multiple_of`` — additional divisibility constraint (default 1).
* ``ladder(upto)`` — the distinct buckets for 1..upto rows; absent, a
  no-args ``warmup()`` compiles only the single ``max_batch`` bucket.

A bare callable without them still serves correctly, but gets those
degraded defaults silently — implement the attributes (or subclass
:class:`Pow2Buckets` / :class:`FixedBuckets`, which carry them) for full
warmup coverage and a mesh-safe memory-cap fallback.
"""

from __future__ import annotations

from typing import Optional, Sequence, Tuple

import numpy as np

__all__ = ["next_pow2", "Pow2Buckets", "FixedBuckets", "bucket_nbytes"]


def next_pow2(n: int) -> int:
    """Smallest power of two >= n (n >= 1)."""
    return 1 << (int(n) - 1).bit_length()


class Pow2Buckets:
    """The default policy: round rows up to a power of two.

    The bucket set is ``{ceil(2^k / multiple_of) * multiple_of : 2^k >=
    min_rows}`` and the policy maps ``rows`` to the smallest member >=
    ``rows`` — which makes it **idempotent** (``policy(policy(n)) ==
    policy(n)``), the property warmup relies on: a warmup request sized to
    a bucket must land back in that same bucket, or warmup compiles the
    wrong programs and traffic recompiles.

    Parameters
    ----------
    min_rows : int
        Floor of the ladder. Sharded programs need the batch axis divisible
        by the mesh axis size, so adapters set ``min_rows`` to the mesh
        size (e.g. ``dp`` for the transformer).
    multiple_of : int
        Every bucket is a multiple of this (covers non-power-of-two mesh
        sizes; 1 = no constraint).
    max_rows : int, optional
        Hard ceiling: the largest bucket is the biggest multiple of
        ``multiple_of`` that is <= ``max_rows``; rows beyond it raise.
    """

    def __init__(self, min_rows: int = 1, multiple_of: int = 1,
                 max_rows: Optional[int] = None):
        if min_rows < 1 or multiple_of < 1:
            raise ValueError("min_rows and multiple_of must be >= 1")
        self.min_rows = int(min_rows)
        self.multiple_of = int(multiple_of)
        self.max_rows = None if max_rows is None else int(max_rows)

    def _round(self, p2: int) -> int:
        return -(-p2 // self.multiple_of) * self.multiple_of

    def __call__(self, rows: int) -> int:
        if rows < 1:
            raise ValueError(f"rows must be >= 1, got {rows}")
        p2 = next_pow2(self.min_rows)
        while self._round(p2) < rows:
            p2 <<= 1
        b = self._round(p2)
        if self.max_rows is not None and b > self.max_rows:
            # clamp to the largest DIVISIBLE bucket under the ceiling —
            # returning a raw max_rows could hand a sharded program a
            # batch axis that does not divide the mesh
            cap = (self.max_rows // self.multiple_of) * self.multiple_of
            if rows <= cap:
                return cap
            raise ValueError(
                f"request of {rows} rows exceeds the bucket ceiling "
                f"({cap}, from max_rows={self.max_rows})")
        return b

    def ladder(self, upto: int) -> Tuple[int, ...]:
        """The distinct buckets this policy produces for 1..upto rows —
        the warmup set (bounded by the ceiling when one is set)."""
        if self.max_rows is not None:
            upto = min(upto,
                       (self.max_rows // self.multiple_of)
                       * self.multiple_of)
        out = []
        r = 1
        while r <= upto:
            b = self(r)
            if not out or b != out[-1]:
                out.append(b)
            r = b + 1
        return tuple(out)

    def __repr__(self) -> str:
        return (f"Pow2Buckets(min_rows={self.min_rows}, "
                f"multiple_of={self.multiple_of}, max_rows={self.max_rows})")


class FixedBuckets:
    """An explicit ascending ladder of bucket sizes."""

    def __init__(self, sizes: Sequence[int]):
        sizes = tuple(sorted(int(s) for s in sizes))
        if not sizes or sizes[0] < 1:
            raise ValueError(f"need at least one positive size, got {sizes}")
        self.sizes = sizes

    def __call__(self, rows: int) -> int:
        for s in self.sizes:
            if s >= rows:
                return s
        raise ValueError(
            f"request of {rows} rows exceeds the largest bucket "
            f"({self.sizes[-1]})")

    def ladder(self, upto: int) -> Tuple[int, ...]:
        return tuple(s for s in self.sizes
                     if s <= self(min(upto, self.sizes[-1])))

    def __repr__(self) -> str:
        return f"FixedBuckets({list(self.sizes)})"


def bucket_nbytes(bucket_rows: int, feat_shape: Tuple[int, ...],
                  dtype) -> int:
    """Input-payload bytes of one padded batch — what the executor checks
    against ``ServeConfig.max_bucket_bytes`` (the memory cap that triggers
    the degraded single-request fallback)."""
    return int(bucket_rows) * int(np.prod(feat_shape, dtype=np.int64) or 1) \
        * np.dtype(dtype).itemsize
