"""Model-family adapters: wrap the repo's models as executor callables.

An executor callable is ``(bucket_rows, *feat) -> rows-leading output`` —
row-independent, shape-stable per bucket, parameters captured by closure
(closed-over ``jax.Array`` leaves become jaxpr constants handed to the
executable as buffers, not baked into HLO). Two families are wired:

* the transformer LM (:func:`transformer_logits_fn` /
  :func:`serve_transformer`) — the full sharded forward
  (``TransformerLM.logits_fn``) with the batch axis over ``dp``;
* the sklearn-layer estimators (:func:`estimator_predict_fn` /
  :func:`serve_estimator`) — ``KMeans.predict``-style nearest-centroid
  assignment and ``KNeighborsClassifier.predict`` voting, re-expressed as
  one ``shard_map`` program over the serving mesh (training data
  replicated once at adapter build, request rows sharded).

The ``serve_*`` helpers return ready-to-go executors whose bucket policy
respects the mesh divisibility constraint (bucket % mesh size == 0) and
whose program-cache token is the mesh identity.
"""

from __future__ import annotations

from typing import Any, Callable, Optional

import numpy as np

import jax
import jax.numpy as jnp

from ..core._compat import shard_map
from ..core.communication import sanitize_comm
from .bucketing import Pow2Buckets
from .executor import ServeConfig, ServingExecutor

__all__ = [
    "transformer_logits_fn",
    "serve_transformer",
    "estimator_predict_fn",
    "serve_estimator",
]


# ---------------------------------------------------------------------- #
# transformer LM                                                         #
# ---------------------------------------------------------------------- #
def transformer_logits_fn(model, params) -> Callable:
    """``(B, S) int32 tokens -> (B, S, vocab) f32 logits`` closure over a
    :class:`~heat_tpu.nn.transformer.TransformerLM` and its params.

    Uses the model's compiled sharded forward (``logits_fn``): batch over
    ``dp``, sequence over ``sp``, heads/features over ``tp`` — so the
    bucket's batch rows must divide by ``dp`` (and ``S`` by ``sp``), which
    :func:`serve_transformer`'s bucket policy guarantees.
    """
    fwd = model.logits_fn()

    def fn(toks):
        return fwd(params, toks)

    return fn


def serve_transformer(model, params, seq_len: int,
                      config: Optional[ServeConfig] = None,
                      decode: bool = False, **kwargs):
    """A configured executor serving ``model``'s forward at ``seq_len``.

    Requests are ``(rows, seq_len)`` int32 token arrays. The default
    bucket policy is powers of two with a floor of ``dp`` (so every
    padded batch divides over the data-parallel axis); pp must be 1 for
    the non-pipelined forward latency path to make sense, but any
    dp x tp grid serves.

    ``decode=True`` returns the continuous-batching
    :class:`~heat_tpu.serve.decode.DecodeEngine` instead — per-request
    autoregressive generation over a slot-based device-resident KV cache
    (``seq_len`` becomes the engine's ``max_seq_len`` capacity bucket;
    extra ``kwargs``: ``slots``, plus anything
    :class:`~heat_tpu.serve.decode.DecodeConfig` takes). ``config`` must
    be None on this path (the engine has its own config type).
    """
    c = model.cfg
    if decode:
        from .decode import DecodeConfig, DecodeEngine

        if config is not None:
            raise ValueError(
                "decode=True takes DecodeConfig kwargs, not a ServeConfig")
        return DecodeEngine(model, params,
                            DecodeConfig(max_seq_len=seq_len, **kwargs),
                            name="transformer-decode")
    if seq_len % max(1, model.sp):
        raise ValueError(
            f"seq_len ({seq_len}) must divide over sp ({model.sp})")
    if config is None:
        # the forward runs the model's microbatch schedule, so every
        # bucket's per-device batch (bucket / dp) must divide n_micro too
        q = getattr(model, "dp_world", model.dp) * max(1, c.n_micro)
        config = ServeConfig(bucket_rows=Pow2Buckets(min_rows=q,
                                                     multiple_of=q))
    token = ("transformer", c.vocab, c.d_model, c.n_layers, seq_len,
             tuple(model.grid.mesh.shape.items()),
             tuple(d.id for d in model.grid.mesh.devices.flatten()))
    ex = ServingExecutor(
        transformer_logits_fn(model, params), config,
        name="transformer", cache_token=token, **kwargs)
    return ex


# ---------------------------------------------------------------------- #
# sklearn-layer estimators                                               #
# ---------------------------------------------------------------------- #
def _centroid_assign_fn(centroids, comm) -> Callable:
    """Nearest-centroid labels (the ``_KCluster.predict`` semantics) as one
    sharded program: request rows over the mesh, centroids replicated; the
    x^2 term is label-invariant and dropped (same trick as
    ``cluster/kmeans.py::_assign_fn``)."""
    c = jnp.asarray(centroids)
    c2 = jnp.sum(c.astype(jnp.float32) * c.astype(jnp.float32), axis=1)[None, :]

    def local(x):
        xc = jax.lax.dot_general(
            x, c.astype(x.dtype),
            dimension_numbers=(((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32)
        return jnp.argmin(c2 - 2.0 * xc, axis=1)

    if comm.size <= 1:
        return local
    return shard_map(local, mesh=comm.mesh, in_specs=comm.spec(2, 0),
                     out_specs=comm.spec(1, 0), check_vma=False)


def _knn_vote_fn(train_x, train_y, k: int, comm) -> Callable:
    """``KNeighborsClassifier.predict`` semantics as one sharded program:
    request rows over the mesh, the (replicated) training set visited once
    per row via a top-k over the distance tile, then the reference's
    majority vote with smallest-label tie-break."""
    from ..classification.kneighborsclassifier import _vote

    xt = jnp.asarray(train_x)
    yt = jnp.asarray(train_y).reshape(-1)
    t2 = jnp.sum(xt.astype(jnp.float32) * xt.astype(jnp.float32),
                 axis=1)[None, :]

    def local(x):
        xc = jax.lax.dot_general(
            x, xt.astype(x.dtype),
            dimension_numbers=(((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32)
        _, idx = jax.lax.top_k(-(t2 - 2.0 * xc), k)
        return _vote(yt[idx], k)

    if comm.size <= 1:
        return local
    return shard_map(local, mesh=comm.mesh, in_specs=comm.spec(2, 0),
                     out_specs=comm.spec(1, 0), check_vma=False)


def estimator_predict_fn(estimator, comm=None) -> Callable:
    """``(rows, d) -> (rows,) labels`` closure over a FITTED estimator.

    Supports the cluster family (anything exposing ``cluster_centers_``:
    KMeans/KMedians/KMedoids) and :class:`KNeighborsClassifier`. Training
    state is replicated onto the serving mesh ONCE here — request handling
    never re-moves it.
    """
    comm = sanitize_comm(comm)
    if hasattr(estimator, "cluster_centers_"):
        centers = estimator.cluster_centers_
        if centers is None:
            raise ValueError("estimator is not fitted (no cluster centers)")
        return _centroid_assign_fn(centers.resplit(None)._logical(), comm)
    if (getattr(estimator, "x", None) is not None
            and hasattr(estimator, "n_neighbors")):
        xt = estimator.x.resplit(None)._logical()
        yt = estimator.y.resplit(None)._logical()
        return _knn_vote_fn(xt, yt, int(estimator.n_neighbors), comm)
    raise TypeError(
        f"no serving adapter for {type(estimator).__name__}: expected a "
        "fitted cluster estimator (cluster_centers_) or "
        "KNeighborsClassifier")


def serve_estimator(estimator, comm=None,
                    config: Optional[ServeConfig] = None,
                    **kwargs) -> ServingExecutor:
    """A configured executor serving ``estimator.predict`` row batches.

    Requests are ``(rows, n_features)`` arrays; results are ``(rows,)``
    label arrays, bitwise-identical to the estimator's own ``predict``
    labels for the same rows (asserted in ``tests/test_serve.py``).
    """
    comm = sanitize_comm(comm)
    if config is None:
        config = ServeConfig(
            bucket_rows=Pow2Buckets(min_rows=comm.size,
                                    multiple_of=comm.size))
    ex = ServingExecutor(
        estimator_predict_fn(estimator, comm), config,
        name=type(estimator).__name__.lower(),
        cache_token=("estimator",) + tuple(comm.cache_key), **kwargs)
    return ex
