"""Serving metrics: latency percentiles, queue depth, batch occupancy,
shed/deadline counts, cache stats — one plain-dict snapshot.

The registry every :class:`~heat_tpu.serve.executor.ServingExecutor`
reports into (executors share :data:`DEFAULT` unless given their own), and
the home of :func:`runtime_stats` — the repo's single observability
surface, exported as ``ht.runtime_stats()``. One call folds together:

* this module's serving figures (latency p50/p95/p99, queue depth, batch
  occupancy, shed count, program-cache stats),
* the resharding plan cache (:func:`heat_tpu.core.resharding.plan_cache_stats`
  rides through unchanged under the ``"resharding"`` key),
* the op-engine alignment counter (``op_engine.align_resplits``) and every
  other process-wide counter from :mod:`heat_tpu.utils.metrics`.

Everything is host-side python state — snapshotting never touches the
device, so it is safe from any thread at any rate.
"""

from __future__ import annotations

import threading
from collections import deque
from typing import Dict, Optional

__all__ = ["ServeMetrics", "DEFAULT", "runtime_stats"]

_WINDOW = 4096  # latency observations kept for the percentile window


def _percentile(sorted_vals, q: float) -> float:
    """Nearest-rank percentile of an ascending list (q in [0, 1])."""
    n = len(sorted_vals)
    idx = min(n - 1, max(0, int(round(q * (n - 1)))))
    return sorted_vals[idx]


class ServeMetrics:
    """Thread-safe serving metrics registry."""

    COUNTERS = ("requests", "batches", "rows", "padded_rows", "shed",
                "deadline_expired", "early_shed", "rate_limited",
                "breaker_rejections", "fallback_single", "errors")

    def __init__(self, name: str = "serve", window: int = _WINDOW):
        self.name = name
        self._lock = threading.Lock()
        self._counters: Dict[str, int] = {k: 0 for k in self.COUNTERS}
        self._latencies = deque(maxlen=window)   # seconds, completed requests
        self._occupancy = deque(maxlen=512)      # rows / bucket per batch

    # ------------------------------------------------------------------ #
    # recording (called by executors)                                    #
    # ------------------------------------------------------------------ #
    def _inc(self, key: str, value: int = 1) -> None:
        from ..utils import metrics as _pm

        with self._lock:
            self._counters[key] += value
        _pm.inc(f"{self.name}.{key}", value)

    def record_request(self, latency_s: float) -> None:
        with self._lock:
            self._counters["requests"] += 1
            self._latencies.append(float(latency_s))
        from ..utils import metrics as _pm

        _pm.inc(f"{self.name}.requests")

    def record_batch(self, n_requests: int, rows: int, bucket: int) -> None:
        with self._lock:
            self._counters["batches"] += 1
            self._counters["rows"] += int(rows)
            self._counters["padded_rows"] += int(bucket) - int(rows)
            if bucket > 0:
                self._occupancy.append(rows / bucket)
        from ..utils import metrics as _pm

        _pm.inc(f"{self.name}.batches")
        _pm.inc(f"{self.name}.rows", int(rows))
        _pm.inc(f"{self.name}.padded_rows", int(bucket) - int(rows))

    def record_shed(self) -> None:
        self._inc("shed")

    def record_deadline_expired(self) -> None:
        self._inc("deadline_expired")

    def record_early_shed(self) -> None:
        """A queued request the EWMA estimator proved cannot meet its
        deadline, shed typed before dispatch (admission control only)."""
        self._inc("early_shed")

    def record_rate_limited(self) -> None:
        self._inc("rate_limited")

    def record_breaker_rejected(self) -> None:
        self._inc("breaker_rejections")

    def record_fallback_single(self) -> None:
        self._inc("fallback_single")

    def record_error(self) -> None:
        self._inc("errors")

    # ------------------------------------------------------------------ #
    # snapshot                                                           #
    # ------------------------------------------------------------------ #
    def snapshot(self, **gauges) -> dict:
        """Plain-dict snapshot; extra keyword gauges (e.g. ``queue_depth``)
        are merged in verbatim."""
        with self._lock:
            counters = dict(self._counters)
            lats = sorted(self._latencies)
            occ = list(self._occupancy)
        out = dict(counters)
        if lats:
            out["latency_ms"] = {
                "count": len(lats),
                "mean": 1e3 * sum(lats) / len(lats),
                "p50": 1e3 * _percentile(lats, 0.50),
                "p95": 1e3 * _percentile(lats, 0.95),
                "p99": 1e3 * _percentile(lats, 0.99),
                "max": 1e3 * lats[-1],
            }
        else:
            out["latency_ms"] = {"count": 0}
        out["batch_occupancy"] = (
            {"count": len(occ), "mean": sum(occ) / len(occ),
             "last": occ[-1]} if occ else {"count": 0})
        out.update(gauges)
        return out

    def reset(self) -> None:
        with self._lock:
            for k in self._counters:
                self._counters[k] = 0
            self._latencies.clear()
            self._occupancy.clear()


#: the registry executors share by default — what ``ht.runtime_stats()`` reads
DEFAULT = ServeMetrics()


def runtime_stats() -> dict:
    """One observability snapshot for the whole process.

    ``ht.runtime_stats()["resharding"]`` is exactly
    :func:`heat_tpu.core.resharding.plan_cache_stats` (aliased through, not
    copied-and-drifted); ``"serve"`` aggregates every live executor's queue
    depth and program cache on top of the shared metrics registry — its
    ``"tenants"`` map folds each live executor's per-tenant admission
    counters (admitted/shed/rate_limited/early_shed/breaker_*, plus the
    breaker state gauge, worst across executors; empty with no
    multi-tenant registry), and its ``"decode"`` map pins the
    continuous-batching engine figures (slots, occupancy, prefills,
    decode_steps, tokens_out, decode_fallbacks —
    ``serve.decode.DECODE_STATS_KEYS``);
    ``"op_engine"`` carries the alignment counter plus the fusion engine's
    figures (``"fusion"`` is exactly :func:`heat_tpu.core.fusion.stats`:
    enabled flag, flush count, fused-op count, their ops-per-flush ratio,
    and the fusion program cache); ``"data_engine"`` is exactly
    :func:`heat_tpu.data.engine.stats` (enabled flag, dispatch/fallback/
    per-op counters and the data-engine program cache —
    ``doc/data_engine.md``); ``"faults"`` is exactly
    :func:`heat_tpu.utils.faults.stats` (armed plan + per-site fire
    counts — empty on a production run; ``doc/robustness.md``);
    ``"counters"`` is the full process-wide
    counter map (includes ``op_engine.align_resplits``,
    ``op_engine.fusion_flushes`` / ``fusion_ops``, ``resharding.plan_hits``
    / ``_misses``, ``serve.*``, ``fusion.program_*``, ``faults.*`` and the
    fallback counters in the robustness matrix).
    """
    from ..core import fusion, resharding
    from ..data import engine as _data_engine
    from ..utils import faults as _faults
    from ..utils import metrics as _pm

    from . import executor as _executor

    from ..utils.program_cache import ProgramCache

    depth = 0
    # init from the cache's own key set: a ProgramCache.stats() key this
    # dict lacks would KeyError the += fold below with live executors
    # (the PR 7 drift) — the stats-shape contract test pins both sides
    cache_stats = {k: 0 for k in ProgramCache.STATS_KEYS}
    n_exec = 0
    caches = {}  # dedupe by identity: executors may SHARE a ProgramCache
    tenants: dict = {}
    _BREAKER_RANK = {"closed": 0, "half_open": 1, "open": 2}
    for ex in _executor.live_executors():
        n_exec += 1
        depth += ex.queue_depth
        caches[id(ex.program_cache)] = ex.program_cache
        # per-tenant admission counters across executors: the DECLARED
        # counter keys sum, the breaker gauge reports the worst state,
        # policy fields (priority/slo_ms/max_queue/rate_limit) keep the
        # first registration seen — summing a quota across executors
        # would report a bound nobody enforces
        from .admission import TENANT_COUNTERS

        for name, st in ex.tenant_stats().items():
            agg = tenants.setdefault(name, {})
            for k, v in st.items():
                if k in TENANT_COUNTERS:
                    agg[k] = agg.get(k, 0) + int(v)
                elif k == "breaker":
                    if k not in agg or _BREAKER_RANK.get(v, 0) > \
                            _BREAKER_RANK.get(agg[k], 0):
                        agg[k] = v
                else:
                    agg.setdefault(k, v)
    for cache in caches.values():
        for k, v in cache.stats().items():
            cache_stats[k] += v
    counters = _pm.counters()
    # continuous-batching decode engines (serve/decode.py): the pinned
    # six-figure snapshot — slot inventory + mean occupancy over the live
    # engines, lifetime counters from the process-wide registry
    from .decode import live_decode_engines

    slots = 0
    occ_num = 0.0
    for eng in live_decode_engines():
        st = eng.stats()
        slots += st["slots"]
        occ_num += st["occupancy"] * st["slots"]
    decode = {
        "slots": slots,
        "occupancy": (occ_num / slots) if slots else 0.0,
        "prefills": int(counters.get("serve.decode_prefills", 0)),
        "decode_steps": int(counters.get("serve.decode_steps", 0)),
        "tokens_out": int(counters.get("serve.decode_tokens_out", 0)),
        "decode_fallbacks": int(counters.get("serve.decode_fallbacks", 0)),
    }
    return {
        "serve": DEFAULT.snapshot(
            queue_depth=depth, executors=n_exec, program_cache=cache_stats,
            tenants=tenants, decode=decode),
        "resharding": resharding.plan_cache_stats(),
        "op_engine": {
            "align_resplits": int(counters.get("op_engine.align_resplits", 0)),
            "fusion": fusion.stats(),
        },
        # tape-compiled data engine (heat_tpu.data): dispatch/fallback
        # counters + its program cache — see doc/data_engine.md
        "data_engine": _data_engine.stats(),
        # fault-injection surface (heat_tpu.utils.faults): armed plan +
        # per-site fire counts — all zeros/empty on a production run
        "faults": _faults.stats(),
        "counters": counters,
    }
