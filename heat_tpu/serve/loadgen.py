"""Open-loop load generation for the serving executor.

Closed-loop clients (submit → wait → submit) cannot overload a server:
their arrival rate collapses to the service rate, which is exactly why
closed-loop benchmarks under-report tail latency. This module generates
**open-loop** traffic — Poisson arrivals on a fixed schedule, submitted
whether or not earlier requests have finished — the arrival model an
executor serving millions of independent users actually faces, and the
only one under which admission control, shedding and deadline handling
can be observed doing their jobs.

* **Seeded-deterministic**: every tenant's arrival schedule and request
  payloads derive from ``numpy.random.default_rng(seed)`` — the same
  seed offers the same request sequence at the same relative times.
* **Per-tenant accounting**: each request's outcome (``ok`` or the typed
  rejection that shed it) and latency (submit → future done, one
  ``time.monotonic()`` clock) land in a per-tenant histogram; the report
  carries p50/p95/p99/max, the outcome breakdown, and — the robustness
  acceptance headline — the count of **untyped** client-visible errors,
  which a correct executor keeps at zero under any overload.
* **Stall injection**: ``stall=(at_s, dur_s)`` pauses the worker
  mid-phase (a device hiccup / GC pause stand-in), deterministically
  forcing the queue past its bound so shed behavior is exercised even
  when the offered rate estimate was conservative.

``scripts/soak_serve.py`` drives this at 1×/2×(/4×) estimated capacity
with fault sites armed and turns the report into pass/fail verdicts; the
tier-1 short form lives in ``tests/test_serve_admission.py``.
"""

from __future__ import annotations

import threading
import time
from dataclasses import dataclass, field
from typing import Optional, Sequence

import numpy as np

from . import errors as _errors

__all__ = ["TenantLoad", "run_open_loop", "estimate_capacity",
           "classify_outcome"]

#: outcome keys every per-tenant report carries (fixed order)
OUTCOMES = ("ok", "overloaded", "rate_limited", "deadline", "circuit_open",
            "closed", "typed_other", "cancelled", "untyped")


def classify_outcome(exc: Optional[BaseException]) -> str:
    """Map a request's terminal exception (None = success) onto the
    outcome taxonomy. Anything outside the typed serve-error family is
    ``untyped`` — the thing the soak acceptance requires to be ZERO."""
    if exc is None:
        return "ok"
    if isinstance(exc, _errors.ServeCircuitOpen):
        return "circuit_open"
    if isinstance(exc, _errors.ServeRateLimited):
        return "rate_limited"
    if isinstance(exc, _errors.ServeDeadlineExceeded):
        return "deadline"
    if isinstance(exc, _errors.ServeOverloaded):
        return "overloaded"
    if isinstance(exc, _errors.ServeClosed):
        return "closed"
    if isinstance(exc, _errors.ServeError):
        return "typed_other"
    return "untyped"


@dataclass
class TenantLoad:
    """One tenant's offered traffic for a phase."""

    tenant: Optional[str]          # None = untagged (default tenant)
    rate_rps: float                # offered Poisson arrival rate
    rows_mix: Sequence[int] = (1, 2, 3)
    deadline_ms: Optional[float] = None  # explicit per-request deadline
    label: Optional[str] = None    # report key; defaults to tenant name

    @property
    def key(self) -> str:
        return self.label or (self.tenant or "default")


def _percentile(sorted_vals, q: float) -> float:
    n = len(sorted_vals)
    idx = min(n - 1, max(0, int(round(q * (n - 1)))))
    return sorted_vals[idx]


@dataclass
class _Recorder:
    lock: threading.Lock = field(default_factory=threading.Lock)
    # key -> list[(outcome, latency_s)]
    recs: dict = field(default_factory=dict)
    untyped_examples: list = field(default_factory=list)

    def record(self, key: str, outcome: str, latency_s: float,
               exc: Optional[BaseException] = None) -> None:
        with self.lock:
            self.recs.setdefault(key, []).append((outcome, latency_s))
            if outcome == "untyped" and len(self.untyped_examples) < 5:
                self.untyped_examples.append(repr(exc)[:200])

    def counts(self) -> int:
        with self.lock:
            return sum(len(v) for v in self.recs.values())


def _gen_thread(ex, load: TenantLoad, duration_s: float, feat_shape,
                dtype, seed: int, rec: _Recorder, offered: dict) -> None:
    rng = np.random.default_rng(seed)
    feat = tuple(int(s) for s in feat_shape)
    # pre-built payload pool: the generator must be able to outrun the
    # server, so per-arrival allocation cost is taken off the hot loop
    pools = {}
    for r in set(int(r) for r in load.rows_mix):
        if np.issubdtype(np.dtype(dtype), np.integer):
            pools[r] = [rng.integers(0, 16, (r,) + feat).astype(dtype)
                        for _ in range(4)]
        else:
            pools[r] = [rng.standard_normal((r,) + feat).astype(dtype)
                        for _ in range(4)]
    mix = [int(r) for r in load.rows_mix]
    key = load.key
    t0 = time.monotonic()
    t_next = t0
    n = 0
    while True:
        t_next += float(rng.exponential(1.0 / load.rate_rps))
        if t_next - t0 > duration_s:
            break
        delay = t_next - time.monotonic()
        if delay > 0:
            time.sleep(delay)
        # open loop: when behind schedule, submit immediately (burst
        # catch-up) — never skip an arrival
        x = pools[mix[n % len(mix)]][n % 4]
        n += 1
        offered[key] = offered.get(key, 0) + 1
        t_sub = time.monotonic()
        try:
            fut = ex.submit(x, deadline_ms=load.deadline_ms,
                            tenant=load.tenant)
        except Exception as exc:
            rec.record(key, classify_outcome(exc),
                       time.monotonic() - t_sub, exc)
            continue

        def _done(f, t_sub=t_sub, key=key):
            t_done = time.monotonic()
            if f.cancelled():
                rec.record(key, "cancelled", t_done - t_sub)
                return
            exc = f.exception()
            rec.record(key, classify_outcome(exc), t_done - t_sub, exc)

        fut.add_done_callback(_done)


def run_open_loop(ex, loads: Sequence[TenantLoad], duration_s: float,
                  feat_shape, dtype=np.float32, seed: int = 0,
                  stall: Optional[tuple] = None,
                  drain_timeout_s: float = 60.0) -> dict:
    """Drive ``ex`` with open-loop Poisson traffic for ``duration_s``.

    One generator thread per :class:`TenantLoad`; ``stall=(at_s, dur_s)``
    pauses the executor's worker for ``dur_s`` starting at ``at_s`` into
    the phase. Returns the per-tenant report (see module docstring).
    Deterministic per ``seed`` up to OS scheduling.
    """
    keys = [load.key for load in loads]
    if len(set(keys)) != len(keys):
        raise ValueError(
            f"TenantLoad report keys must be unique (got {keys}); "
            "set label= to disambiguate two loads on one tenant")
    rec = _Recorder()
    offered: dict = {}
    threads = [
        threading.Thread(
            target=_gen_thread,
            args=(ex, load, duration_s, feat_shape, dtype,
                  seed + 7919 * i, rec, offered),
            name=f"loadgen-{load.key}", daemon=True)
        for i, load in enumerate(loads)
    ]
    stall_th = None
    if stall is not None:
        at_s, dur_s = stall

        def _stall():
            time.sleep(at_s)
            ex.pause()
            time.sleep(dur_s)
            ex.resume()

        stall_th = threading.Thread(target=_stall, name="loadgen-stall",
                                    daemon=True)
    t0 = time.monotonic()
    for th in threads:
        th.start()
    if stall_th is not None:
        stall_th.start()
    for th in threads:
        th.join(duration_s + drain_timeout_s)
    if stall_th is not None:
        stall_th.join(duration_s + drain_timeout_s)
    # drain: every admitted request must terminate (result or typed
    # error) before the report is cut
    ex.flush(timeout=drain_timeout_s)
    deadline = time.monotonic() + drain_timeout_s
    total_offered = sum(offered.values())
    while rec.counts() < total_offered and time.monotonic() < deadline:
        time.sleep(0.01)
    wall = time.monotonic() - t0

    report = {"duration_s": round(duration_s, 3),
              "wall_s": round(wall, 3),
              "seed": int(seed),
              "stall": list(stall) if stall is not None else None,
              "tenants": {}, "totals": {}}
    tot = {k: 0 for k in OUTCOMES}
    tot_offered = 0
    for load in loads:
        key = load.key
        entries = rec.recs.get(key, [])
        out = {k: 0 for k in OUTCOMES}
        lats = []
        for outcome, lat in entries:
            out[outcome] += 1
            if outcome == "ok":
                lats.append(lat)
        lats.sort()
        n_off = int(offered.get(key, 0))
        tot_offered += n_off
        for k in OUTCOMES:
            tot[k] += out[k]
        shed = sum(out[k] for k in ("overloaded", "rate_limited",
                                    "deadline", "circuit_open"))
        t_report = {
            "offered": n_off,
            "offered_rps": round(n_off / max(wall, 1e-9), 1),
            "target_rps": round(float(load.rate_rps), 1),
            "answered": len(entries),
            "shed": shed,
            "outcomes": out,
        }
        if lats:
            t_report["latency_ms"] = {
                "count": len(lats),
                "p50": round(1e3 * _percentile(lats, 0.50), 2),
                "p95": round(1e3 * _percentile(lats, 0.95), 2),
                "p99": round(1e3 * _percentile(lats, 0.99), 2),
                "max": round(1e3 * lats[-1], 2),
            }
        else:
            t_report["latency_ms"] = {"count": 0}
        report["tenants"][key] = t_report
    report["totals"] = {
        "offered": tot_offered,
        "answered": sum(tot.values()),
        "shed": sum(tot[k] for k in ("overloaded", "rate_limited",
                                     "deadline", "circuit_open")),
        "untyped": tot["untyped"],
        "outcomes": tot,
    }
    if rec.untyped_examples:
        report["totals"]["untyped_examples"] = rec.untyped_examples
    return report


def estimate_capacity(ex, feat_shape, rows: int = 1, dtype=np.float32,
                      n: int = 96, seed: int = 0,
                      timeout_s: float = 120.0) -> float:
    """Closed-loop batched throughput estimate (requests/s): submit ``n``
    same-shape requests as fast as possible and wait for all — the
    coalesced service rate the soak phases scale their offered load
    against. Run AFTER ``warmup()`` (compiles would dominate); keep
    ``n`` below the executor's ``queue_limit`` or the estimate sheds."""
    rng = np.random.default_rng(seed)
    feat = tuple(int(s) for s in feat_shape)
    if np.issubdtype(np.dtype(dtype), np.integer):
        x = rng.integers(0, 16, (int(rows),) + feat).astype(dtype)
    else:
        x = rng.standard_normal((int(rows),) + feat).astype(dtype)
    t0 = time.monotonic()
    futs = [ex.submit(x) for _ in range(int(n))]
    for f in futs:
        f.result(timeout_s)
    wall = time.monotonic() - t0
    return n / max(wall, 1e-9)
