"""heat_tpu — a TPU-native distributed n-dimensional array framework.

Brand-new implementation of the capabilities of Heat (baurse/heat, see
SURVEY.md): a NumPy-like distributed ``DNDarray`` with a ``split`` axis, the
full elementwise/reduction/manipulation/linalg/statistics op surface, a
counter-based parallel RNG, parallel I/O, an sklearn-style ML layer, and
data-parallel NN training — architected for TPU: local tensors are
``jax.Array`` shards on a pjit mesh, the MPI layer is replaced by an ICI/DCN
collective facade (``jax.lax`` collectives under GSPMD/shard_map), and hot
kernels drop to Pallas.

Usage matches the reference: ``import heat_tpu as ht``.
"""

import os as _os

# x64 must be enabled before any tracing so the int64/float64 members of the
# type lattice are real (JAX disables them by default).
import jax as _jax

_jax.config.update("jax_enable_x64", True)

# TPU MXU default is one-pass bf16 for float32 matmuls (~1e-3 relative
# error) — far below what a NumPy-surface framework may silently return.
# "high" (bf16_3x) restores ~1e-5 accuracy and benches *faster* than the
# default on v5e; bf16 inputs are unaffected. Users can override by setting
# the flag themselves before import (we only fill in the unset default).
if _jax.config.jax_default_matmul_precision is None:
    _jax.config.update("jax_default_matmul_precision", "high")

# The CPU backend dispatches executables asynchronously; two in-flight
# programs with collectives can interleave their in-process rendezvous and
# deadlock (XLA CPU rendezvous timeout -> hard abort; observed with the
# kmeans++ seeding programs racing the Lloyd step on an 8-device host
# mesh). Serial dispatch on CPU removes the race; TPU is unaffected. Set
# before backend init (importing heat_tpu does not initialize a backend).
try:
    _jax.config.update("jax_cpu_enable_async_dispatch", False)
except Exception:  # unknown flag on some jax versions: keep going
    pass

from .core import *
from . import core
from .core import communication, devices, types, factories, manipulations, linalg
from .core import random
from . import cluster
from . import classification
from . import graph
from . import naive_bayes
from . import regression
from . import spatial
from . import nn
from . import optim
from . import utils
from . import serve
from . import data

__version__ = core.__version__


def runtime_stats() -> dict:
    """The process's one observability snapshot: serving figures (latency
    percentiles, queue depth, batch occupancy, shed count, program-cache
    stats), the resharding plan cache (``"resharding"`` is exactly
    :func:`heat_tpu.core.resharding.plan_cache_stats` — the supported alias
    for it), the op-engine alignment counter and fusion-engine figures
    (``["op_engine"]["fusion"]`` is exactly
    :func:`heat_tpu.core.fusion.stats`: flushes, fused ops, ops-per-flush,
    program-cache hit/miss/compile — see ``doc/fusion.md``), and every
    process-wide counter. See :mod:`heat_tpu.serve.metrics`."""
    from .serve.metrics import runtime_stats as _rs

    return _rs()


def __getattr__(name):
    # lazy world communicators (constructing them initializes the XLA
    # backend, which must not happen at import time — see distributed_init)
    if name in ("MESH_WORLD", "MESH_SELF"):
        return getattr(communication, name)
    raise AttributeError(f"module 'heat_tpu' has no attribute {name!r}")
