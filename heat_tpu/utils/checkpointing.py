"""Checkpoint / resume subsystem.

The reference has **no unified checkpoint subsystem** (SURVEY.md §5): tensor
save/load goes through ``ht.save``/``ht.load`` and optimizer state through
``DetectMetricPlateau.get_state/set_state``; model checkpointing is left to
user scripts. This module exceeds that: one API that checkpoints DNDarrays,
arbitrary JAX pytrees (flax params / optax states), and estimator state,
with atomic writes for crash safety.
"""

from __future__ import annotations

import json
import os
import tempfile
from typing import Any, Dict, Optional

import numpy as np

import jax
import jax.numpy as jnp

from ..core import factories, types
from ..core.dndarray import DNDarray

__all__ = ["save_checkpoint", "load_checkpoint", "checkpoint_estimator", "restore_estimator"]

_MANIFEST = "manifest.json"

# namedtuple classes from these top-level modules are reconstructed on
# restore; extend (e.g. ``NAMEDTUPLE_ALLOWLIST.add("mytrainlib")``) to restore
# custom state classes — anything else degrades to a plain tuple with a warning
NAMEDTUPLE_ALLOWLIST = {"optax", "flax", "jax", "heat_tpu", "chex"}


def _flatten(tree, prefix=""):
    """Flatten nested dicts/lists/tuples of arrays into (path → leaf, spec).

    ``spec`` is a JSON-serializable structure descriptor so containers
    round-trip with their exact types (optax states are tuples)."""
    out = {}
    if isinstance(tree, dict):
        spec = {"kind": "dict", "items": {}}
        for k, v in tree.items():
            sub, subspec = _flatten(v, f"{prefix}/{k}" if prefix else str(k))
            out.update(sub)
            spec["items"][k] = subspec
    elif isinstance(tree, (list, tuple)):
        if isinstance(tree, tuple) and hasattr(tree, "_fields"):
            # namedtuple (optax/flax states): record the class for rebuild
            cls = type(tree)
            spec = {"kind": "namedtuple", "cls": [cls.__module__, cls.__qualname__], "items": []}
        else:
            spec = {"kind": "tuple" if isinstance(tree, tuple) else "list", "items": []}
        for i, v in enumerate(tree):
            sub, subspec = _flatten(v, f"{prefix}/{i}" if prefix else str(i))
            out.update(sub)
            spec["items"].append(subspec)
    else:
        spec = {"kind": "leaf", "path": prefix}
        out[prefix] = tree
    return out, spec


def save_checkpoint(path: str, state: Dict[str, Any], step: Optional[int] = None) -> None:
    """Write ``state`` (a dict of DNDarrays, pytrees, or scalars) atomically.

    Layout: ``<path>/manifest.json`` plus one ``.npz`` holding every array
    leaf. DNDarray split/dtype metadata is preserved for exact restore.
    """
    os.makedirs(path, exist_ok=True)
    arrays: Dict[str, np.ndarray] = {}
    manifest: Dict[str, Any] = {"step": step, "entries": {}}

    for name, value in state.items():
        if isinstance(value, DNDarray):
            arrays[name] = value.numpy()
            manifest["entries"][name] = {
                "kind": "dndarray",
                "split": value.split,
                "dtype": value.dtype.__name__,
            }
        elif isinstance(value, (int, float, str, bool)) or value is None:
            manifest["entries"][name] = {"kind": "scalar", "value": value}
        else:
            # arbitrary pytree (flax params, optax state); DNDarray leaves
            # keep their split/dtype metadata so they restore as DNDarrays
            leaves, spec = _flatten(value)
            keys = {}
            for leaf_path, leaf in leaves.items():
                arr_key = f"{name}::{leaf_path}"
                if isinstance(leaf, DNDarray):
                    arrays[arr_key] = leaf.numpy()
                    keys[leaf_path] = {
                        "kind": "dndarray",
                        "split": leaf.split,
                        "dtype": leaf.dtype.__name__,
                    }
                else:
                    arrays[arr_key] = np.asarray(leaf)
                    keys[leaf_path] = {"kind": "array"}
            manifest["entries"][name] = {"kind": "pytree", "leaves": keys, "spec": spec}

    tmp_fd, tmp_npz = tempfile.mkstemp(dir=path, suffix=".tmp.npz")
    os.close(tmp_fd)
    np.savez(tmp_npz, **arrays)
    os.replace(tmp_npz, os.path.join(path, "arrays.npz"))

    tmp_fd, tmp_json = tempfile.mkstemp(dir=path, suffix=".json.tmp")
    with os.fdopen(tmp_fd, "w") as f:
        json.dump(manifest, f)
    os.replace(tmp_json, os.path.join(path, _MANIFEST))


def _unflatten(leaves: Dict[str, Any], spec=None):
    """Rebuild the container structure from path → restored leaf.

    With a ``spec`` (new manifests), container types (dict/list/tuple) are
    reconstructed exactly; without one (legacy manifests) nested dicts with
    string keys are returned."""
    if spec is not None:
        if spec["kind"] == "leaf":
            return leaves[spec["path"]]
        if spec["kind"] == "dict":
            return {k: _unflatten(leaves, s) for k, s in spec["items"].items()}
        rebuilt = [_unflatten(leaves, s) for s in spec["items"]]
        if spec["kind"] == "namedtuple":
            import importlib
            import warnings

            def degrade(reason):
                warnings.warn(
                    f"checkpoint namedtuple {spec['cls']} restored as a plain "
                    f"tuple ({reason}); extend "
                    f"heat_tpu.utils.checkpointing.NAMEDTUPLE_ALLOWLIST to "
                    f"restore custom state classes",
                    stacklevel=2,
                )
                return tuple(rebuilt)

            try:
                mod, qualname = spec["cls"]
                # manifests are data, not code: only resolve classes from
                # allowlisted modules, and only call genuine NamedTuples
                if mod.partition(".")[0] not in NAMEDTUPLE_ALLOWLIST:
                    return degrade("module not in allowlist")
                cls = importlib.import_module(mod)
                for part in qualname.split("."):
                    cls = getattr(cls, part)
                if not (isinstance(cls, type) and issubclass(cls, tuple) and hasattr(cls, "_fields")):
                    return degrade("not a NamedTuple class")
                return cls(*rebuilt)
            except (ImportError, AttributeError):
                return degrade("class not importable")
        return tuple(rebuilt) if spec["kind"] == "tuple" else rebuilt
    root: Dict[str, Any] = {}
    for path, leaf in leaves.items():
        parts = path.split("/")
        node = root
        for p in parts[:-1]:
            node = node.setdefault(p, {})
        node[parts[-1]] = leaf
    return root


def load_checkpoint(path: str) -> Dict[str, Any]:
    """Restore a checkpoint written by :func:`save_checkpoint`."""
    with open(os.path.join(path, _MANIFEST)) as f:
        manifest = json.load(f)
    with np.load(os.path.join(path, "arrays.npz")) as npz:
        arrays = {k: npz[k] for k in npz.files}

    state: Dict[str, Any] = {"__step__": manifest.get("step")}
    for name, meta in manifest["entries"].items():
        if meta["kind"] == "dndarray":
            state[name] = factories.array(
                arrays[name],
                dtype=getattr(types, meta["dtype"]),
                split=meta["split"],
            )
        elif meta["kind"] == "scalar":
            state[name] = meta["value"]
        else:
            leaf_meta = meta["leaves"]
            if isinstance(leaf_meta, list):  # legacy manifests: plain arrays
                leaf_meta = {p: {"kind": "array"} for p in leaf_meta}
            leaves: Dict[str, Any] = {}
            for leaf_path, lm in leaf_meta.items():
                raw = arrays[f"{name}::{leaf_path}"]
                if lm["kind"] == "dndarray":
                    leaves[leaf_path] = factories.array(
                        raw, dtype=getattr(types, lm["dtype"]), split=lm["split"]
                    )
                else:
                    leaves[leaf_path] = jnp.asarray(raw)
            state[name] = _unflatten(leaves, meta.get("spec"))
    return state


def checkpoint_estimator(path: str, estimator, step: Optional[int] = None) -> None:
    """Checkpoint an sklearn-style estimator's params + learned state."""
    state: Dict[str, Any] = {}
    for key, value in vars(estimator).items():
        clean = key.split("__")[-1] if "__" in key else key
        if isinstance(value, DNDarray):
            state[f"attr:{clean}"] = value
        elif isinstance(value, (int, float, str, bool)) or value is None:
            state[f"attr:{clean}"] = value
    state["__class__"] = type(estimator).__name__
    save_checkpoint(path, state, step=step)


def restore_estimator(path: str, estimator):
    """Restore attributes saved by :func:`checkpoint_estimator` in place."""
    state = load_checkpoint(path)
    cls = state.pop("__class__", None)
    if cls is not None and cls != type(estimator).__name__:
        raise TypeError(f"checkpoint holds a {cls}, not a {type(estimator).__name__}")
    state.pop("__step__", None)
    for key, value in state.items():
        if key.startswith("attr:"):
            name = key[len("attr:"):]
            # find the matching (possibly name-mangled) attribute
            for attr in vars(estimator):
                if attr == name or attr.endswith("__" + name):
                    setattr(estimator, attr, value)
                    break
            else:
                setattr(estimator, name, value)
    return estimator
