"""Checkpoint / resume subsystem.

The reference has **no unified checkpoint subsystem** (SURVEY.md §5): tensor
save/load goes through ``ht.save``/``ht.load`` and optimizer state through
``DetectMetricPlateau.get_state/set_state``; model checkpointing is left to
user scripts. This module exceeds that: one API that checkpoints DNDarrays,
arbitrary JAX pytrees (flax params / optax states), and estimator state,
with atomic writes for crash safety.
"""

from __future__ import annotations

import json
import os
import tempfile
from typing import Any, Dict, Optional

import numpy as np

import jax
import jax.numpy as jnp

from ..core import factories, types
from ..core.dndarray import DNDarray

__all__ = [
    "save_checkpoint",
    "load_checkpoint",
    "checkpoint_estimator",
    "restore_estimator",
    "CheckpointManager",
    "run_with_recovery",
]

_MANIFEST = "manifest.json"

# namedtuple classes from these top-level modules are reconstructed on
# restore; extend (e.g. ``NAMEDTUPLE_ALLOWLIST.add("mytrainlib")``) to restore
# custom state classes — anything else degrades to a plain tuple with a warning
NAMEDTUPLE_ALLOWLIST = {"optax", "flax", "jax", "heat_tpu", "chex"}


def _flatten(tree, prefix=""):
    """Flatten nested dicts/lists/tuples of arrays into (path → leaf, spec).

    ``spec`` is a JSON-serializable structure descriptor so containers
    round-trip with their exact types (optax states are tuples)."""
    out = {}
    if isinstance(tree, dict):
        spec = {"kind": "dict", "items": {}}
        for k, v in tree.items():
            sub, subspec = _flatten(v, f"{prefix}/{k}" if prefix else str(k))
            out.update(sub)
            spec["items"][k] = subspec
    elif isinstance(tree, (list, tuple)):
        if isinstance(tree, tuple) and hasattr(tree, "_fields"):
            # namedtuple (optax/flax states): record the class for rebuild
            cls = type(tree)
            spec = {"kind": "namedtuple", "cls": [cls.__module__, cls.__qualname__], "items": []}
        else:
            spec = {"kind": "tuple" if isinstance(tree, tuple) else "list", "items": []}
        for i, v in enumerate(tree):
            sub, subspec = _flatten(v, f"{prefix}/{i}" if prefix else str(i))
            out.update(sub)
            spec["items"].append(subspec)
    else:
        spec = {"kind": "leaf", "path": prefix}
        out[prefix] = tree
    return out, spec


def save_checkpoint(path: str, state: Dict[str, Any], step: Optional[int] = None) -> None:
    """Write ``state`` (a dict of DNDarrays, pytrees, or scalars) atomically.

    Layout: ``<path>/manifest.json`` plus one ``.npz`` holding every array
    leaf. DNDarray split/dtype metadata is preserved for exact restore.
    """
    os.makedirs(path, exist_ok=True)
    arrays: Dict[str, np.ndarray] = {}
    manifest: Dict[str, Any] = {"step": step, "entries": {}}

    for name, value in state.items():
        if isinstance(value, DNDarray):
            arrays[name] = value.numpy()
            manifest["entries"][name] = {
                "kind": "dndarray",
                "split": value.split,
                "dtype": value.dtype.__name__,
            }
        elif isinstance(value, (int, float, str, bool)) or value is None:
            manifest["entries"][name] = {"kind": "scalar", "value": value}
        else:
            # arbitrary pytree (flax params, optax state); DNDarray leaves
            # keep their split/dtype metadata so they restore as DNDarrays
            leaves, spec = _flatten(value)
            keys = {}
            for leaf_path, leaf in leaves.items():
                arr_key = f"{name}::{leaf_path}"
                if isinstance(leaf, DNDarray):
                    arrays[arr_key] = leaf.numpy()
                    keys[leaf_path] = {
                        "kind": "dndarray",
                        "split": leaf.split,
                        "dtype": leaf.dtype.__name__,
                    }
                else:
                    arrays[arr_key] = np.asarray(leaf)
                    keys[leaf_path] = {"kind": "array"}
            manifest["entries"][name] = {"kind": "pytree", "leaves": keys, "spec": spec}

    # leaf payload FIRST, manifest LAST: a manifest is the completeness
    # marker (all_steps()/restore() key on it), so it must never become
    # visible before the arrays it describes
    _atomic_write(path, "arrays.npz", ".tmp.npz",
                  lambda tmp: np.savez(tmp, **arrays),
                  "checkpoint.leaf.write")

    def _write_manifest(tmp):
        with open(tmp, "w") as f:
            json.dump(manifest, f)

    _atomic_write(path, _MANIFEST, ".json.tmp", _write_manifest,
                  "checkpoint.manifest.write")


def _atomic_write(dirpath: str, final_name: str, suffix: str, write_fn,
                  site: str) -> None:
    """Write ``final_name`` atomically (temp file + ``os.replace``),
    retrying ONCE on an IO error.

    HARDENED FAILURE DOMAIN (doc/robustness.md): a transient ``OSError``
    (NFS blip, fd exhaustion) gets one retry on a fresh temp file,
    counted as ``checkpoint.write_retries``; a second failure re-raises.
    In every outcome the temp file is unlinked and the final name is
    either the complete new payload or untouched — a partial write is
    never visible under the real name."""
    from . import faults as _faults
    from . import metrics as _metrics

    for attempt in (1, 2):
        fd, tmp = tempfile.mkstemp(dir=dirpath, suffix=suffix)
        os.close(fd)
        try:
            _faults.check(site)
            write_fn(tmp)
            os.replace(tmp, os.path.join(dirpath, final_name))
            return
        except BaseException as exc:
            # the temp file never survives, whatever went wrong —
            # only a transient OSError earns the one retry
            try:
                os.unlink(tmp)
            except OSError:
                pass
            if not isinstance(exc, OSError) or attempt == 2:
                raise
            _metrics.inc("checkpoint.write_retries")


def _unflatten(leaves: Dict[str, Any], spec=None):
    """Rebuild the container structure from path → restored leaf.

    With a ``spec`` (new manifests), container types (dict/list/tuple) are
    reconstructed exactly; without one (legacy manifests) nested dicts with
    string keys are returned."""
    if spec is not None:
        if spec["kind"] == "leaf":
            return leaves[spec["path"]]
        if spec["kind"] == "dict":
            return {k: _unflatten(leaves, s) for k, s in spec["items"].items()}
        rebuilt = [_unflatten(leaves, s) for s in spec["items"]]
        if spec["kind"] == "namedtuple":
            import importlib
            import warnings

            def degrade(reason):
                warnings.warn(
                    f"checkpoint namedtuple {spec['cls']} restored as a plain "
                    f"tuple ({reason}); extend "
                    f"heat_tpu.utils.checkpointing.NAMEDTUPLE_ALLOWLIST to "
                    f"restore custom state classes",
                    stacklevel=2,
                )
                return tuple(rebuilt)

            try:
                mod, qualname = spec["cls"]
                # manifests are data, not code: only resolve classes from
                # allowlisted modules, and only call genuine NamedTuples
                if mod.partition(".")[0] not in NAMEDTUPLE_ALLOWLIST:
                    return degrade("module not in allowlist")
                cls = importlib.import_module(mod)
                for part in qualname.split("."):
                    cls = getattr(cls, part)
                if not (isinstance(cls, type) and issubclass(cls, tuple) and hasattr(cls, "_fields")):
                    return degrade("not a NamedTuple class")
                return cls(*rebuilt)
            except (ImportError, AttributeError):
                return degrade("class not importable")
        return tuple(rebuilt) if spec["kind"] == "tuple" else rebuilt
    root: Dict[str, Any] = {}
    for path, leaf in leaves.items():
        parts = path.split("/")
        node = root
        for p in parts[:-1]:
            node = node.setdefault(p, {})
        node[parts[-1]] = leaf
    return root


def load_checkpoint(path: str) -> Dict[str, Any]:
    """Restore a checkpoint written by :func:`save_checkpoint`."""
    from . import faults as _faults

    _faults.check("checkpoint.manifest.read")
    with open(os.path.join(path, _MANIFEST)) as f:
        manifest = json.load(f)
    _faults.check("checkpoint.leaf.read")
    with np.load(os.path.join(path, "arrays.npz")) as npz:
        arrays = {k: npz[k] for k in npz.files}

    state: Dict[str, Any] = {"__step__": manifest.get("step")}
    for name, meta in manifest["entries"].items():
        if meta["kind"] == "dndarray":
            state[name] = factories.array(
                arrays[name],
                dtype=getattr(types, meta["dtype"]),
                split=meta["split"],
            )
        elif meta["kind"] == "scalar":
            state[name] = meta["value"]
        else:
            leaf_meta = meta["leaves"]
            if isinstance(leaf_meta, list):  # legacy manifests: plain arrays
                leaf_meta = {p: {"kind": "array"} for p in leaf_meta}
            leaves: Dict[str, Any] = {}
            for leaf_path, lm in leaf_meta.items():
                raw = arrays[f"{name}::{leaf_path}"]
                if lm["kind"] == "dndarray":
                    leaves[leaf_path] = factories.array(
                        raw, dtype=getattr(types, lm["dtype"]), split=lm["split"]
                    )
                else:
                    leaves[leaf_path] = jnp.asarray(raw)
            state[name] = _unflatten(leaves, meta.get("spec"))
    return state


def checkpoint_estimator(path: str, estimator, step: Optional[int] = None) -> None:
    """Checkpoint an sklearn-style estimator's params + learned state."""
    state: Dict[str, Any] = {}
    for key, value in vars(estimator).items():
        clean = key.split("__")[-1] if "__" in key else key
        if isinstance(value, DNDarray):
            state[f"attr:{clean}"] = value
        elif isinstance(value, (int, float, str, bool)) or value is None:
            state[f"attr:{clean}"] = value
    state["__class__"] = type(estimator).__name__
    save_checkpoint(path, state, step=step)


def restore_estimator(path: str, estimator):
    """Restore attributes saved by :func:`checkpoint_estimator` in place."""
    state = load_checkpoint(path)
    cls = state.pop("__class__", None)
    if cls is not None and cls != type(estimator).__name__:
        raise TypeError(f"checkpoint holds a {cls}, not a {type(estimator).__name__}")
    state.pop("__step__", None)
    for key, value in state.items():
        if key.startswith("attr:"):
            name = key[len("attr:"):]
            # find the matching (possibly name-mangled) attribute
            for attr in vars(estimator):
                if attr == name or attr.endswith("__" + name):
                    setattr(estimator, attr, value)
                    break
            else:
                setattr(estimator, name, value)
    return estimator


class CheckpointManager:
    """Rotating training-loop checkpoints with resume discovery.

    The reference has no failure-detection/elastic-recovery story at all —
    a rank failure kills the MPI job and training restarts from scratch
    (SURVEY.md §5). This manager provides the TPU-native equivalent of a
    restartable loop: periodic atomic checkpoints (``save`` respects
    ``every_steps``), keep-last-``keep`` rotation, and ``restore`` of the
    newest complete checkpoint after a crash or preemption.

    >>> mgr = CheckpointManager("/tmp/run", every_steps=100, keep=3)
    >>> start, state = mgr.restore() or (0, init_state())
    >>> for step in range(start, total):
    ...     state = train_step(state)
    ...     mgr.save(step + 1, state)
    """

    def __init__(self, directory: str, every_steps: int = 1, keep: int = 3):
        if every_steps < 1 or keep < 1:
            raise ValueError("every_steps and keep must be >= 1")
        self.directory = directory
        self.every_steps = every_steps
        self.keep = keep
        os.makedirs(directory, exist_ok=True)

    def _path(self, step: int) -> str:
        return os.path.join(self.directory, f"ckpt_{step:012d}")

    def all_steps(self):
        """Steps with a complete (manifest present) checkpoint, ascending."""
        steps = []
        for name in os.listdir(self.directory):
            if name.startswith("ckpt_") and os.path.exists(
                    os.path.join(self.directory, name, _MANIFEST)):
                try:
                    steps.append(int(name[5:]))
                except ValueError:
                    continue
        return sorted(steps)

    def latest_step(self) -> Optional[int]:
        steps = self.all_steps()
        return steps[-1] if steps else None

    def save(self, step: int, state: Dict[str, Any], force: bool = False) -> bool:
        """Checkpoint ``state`` at ``step`` if due (or ``force``); rotates
        old checkpoints. Returns True when a checkpoint was written."""
        if not force and step % self.every_steps != 0:
            return False
        save_checkpoint(self._path(step), state, step=step)
        for old in self.all_steps()[:-self.keep]:
            _rmtree(self._path(old))
        # also sweep orphans: ckpt dirs without a manifest are dead partial
        # writes from a crash mid-save; they would otherwise accumulate
        # (all_steps() never lists them, so rotation alone misses them)
        for name in os.listdir(self.directory):
            full = os.path.join(self.directory, name)
            # quarantined (".corrupt") directories are evidence, not
            # orphans: restore() renamed them on purpose — keep them
            if (name.startswith("ckpt_") and not name.endswith(".corrupt")
                    and full != self._path(step)
                    and not os.path.exists(os.path.join(full, _MANIFEST))):
                _rmtree(full)
        return True

    def restore(self):
        """(step, state) of the newest complete checkpoint, or None.

        HARDENED FAILURE DOMAIN (doc/robustness.md): a checkpoint that
        fails to load gets ONE immediate re-read first — a transient IO
        error must not condemn good data (``checkpoint.read_retries``).
        A checkpoint that fails twice (bad manifest JSON, missing or
        truncated leaf payload) is real corruption: the directory is
        QUARANTINED under a ``.corrupt`` rename — so it stops being a
        restore candidate but survives on disk for the postmortem — and
        restore falls back to the newest older good step
        (``checkpoint.corrupt_skipped``), the elastic-recovery path. The
        returned state is exactly what was saved (the manifest's step is
        reported separately, not injected into the dict).
        """
        import warnings

        from . import metrics as _metrics

        for step in reversed(self.all_steps()):
            state = err = None
            for attempt in (1, 2):
                try:
                    state = load_checkpoint(self._path(step))
                    break
                except Exception as exc:
                    err = exc
                    if attempt == 1:
                        _metrics.inc("checkpoint.read_retries")
            if state is None:
                _metrics.inc("checkpoint.corrupt_skipped")
                warnings.warn(
                    f"skipping corrupt checkpoint step {step} at "
                    f"{self._path(step)} (quarantined as .corrupt): "
                    f"{err!r}")
                self._quarantine(step)
                continue
            state.pop("__step__", None)
            return step, state
        return None

    def _quarantine(self, step: int) -> None:
        """Rename a corrupt checkpoint dir out of the restore candidate
        set (best-effort: a read-only filesystem must not turn recovery
        into a second failure)."""
        src = self._path(step)
        dst = src + ".corrupt"
        n = 1
        while os.path.exists(dst):
            dst = f"{src}.corrupt.{n}"
            n += 1
        try:
            os.rename(src, dst)
        except OSError:
            pass


def _rmtree(path: str) -> None:
    import shutil

    shutil.rmtree(path, ignore_errors=True)


def run_with_recovery(train_fn, manager: CheckpointManager, init_state,
                      max_restarts: int = 3, backoff_s: float = 0.05,
                      max_failures: Optional[int] = None):
    """Run a restartable training loop with crash recovery.

    ``train_fn(state, start_step, save) -> state`` runs the loop body; it
    must call ``save(step, state)`` as it goes (the manager's cadence
    applies) and may raise at any point. On an exception the loop restarts
    from the newest checkpoint — the single-controller analogue of elastic
    training (the reference's MPI SPMD model cannot do this at all;
    SURVEY.md §5 "failure detection: none").

    Restarts are BOUNDED and PACED: at most ``max_restarts`` (default 3;
    the exceeding failure re-raises), with exponential backoff between
    attempts (``backoff_s`` base, doubling per restart, capped at 30 s) so
    a hard-failing step does not spin the loop at CPU speed against the
    same broken state. Each restart counts
    ``checkpoint.recovery_restarts`` in :mod:`heat_tpu.utils.metrics`
    (visible in ``ht.runtime_stats()["counters"]``). ``max_failures`` is
    the historic name for ``max_restarts`` and is honored as an alias.
    """
    import time

    from . import metrics as _metrics

    if max_failures is not None:
        max_restarts = max_failures
    restarts = 0
    while True:
        restored = manager.restore()
        # fresh copy per attempt: a crashed train_fn that mutated the
        # initial state in place must not leak into the retry
        start, state = restored if restored else (0, _fresh_state(init_state))
        try:
            return train_fn(state, start, manager.save)
        except Exception:
            restarts += 1
            if restarts > max_restarts:
                raise
            _metrics.inc("checkpoint.recovery_restarts")
            time.sleep(min(30.0, backoff_s * (2.0 ** (restarts - 1))))


def _fresh_state(tree):
    """Structure-fresh copy of a state pytree: every container is rebuilt
    (so in-place container mutations cannot leak across retries) while
    immutable leaves (jax.Array, scalars) are shared. Mutable leaves are
    copied: numpy arrays by value, DNDarrays re-wrapped (their backing
    jax.Array is immutable; comm/mesh are shared — a whole-tree deepcopy
    would choke on device handles and round-trip arrays through the host),
    and any other leaf (set, bytearray, custom object) by best-effort
    deepcopy so a crashed attempt's mutations cannot leak either. Leaves
    that refuse to deepcopy (locks, open handles, device-handle-bearing
    objects) are shared unchanged rather than breaking startup — such
    leaves must not be mutated by train_fn."""
    import copy

    def leaf(x):
        if isinstance(x, jax.Array) or isinstance(
                x, (int, float, complex, bool, str, bytes, type(None))):
            return x
        if isinstance(x, np.ndarray):
            return x.copy()
        if isinstance(x, DNDarray):
            return DNDarray(x.larray, x.gshape, x.dtype, x.split, x.device, x.comm)
        try:
            return copy.deepcopy(x)
        except Exception as exc:
            import warnings

            warnings.warn(
                f"run_with_recovery: state leaf of type {type(x).__name__} "
                f"could not be copied ({exc!r}) and is SHARED across retry "
                "attempts — it must not be mutated by train_fn")
            return x

    return jax.tree.map(leaf, tree, is_leaf=lambda x: isinstance(x, DNDarray))
