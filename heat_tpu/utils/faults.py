"""Deterministic fault injection: prove every failure domain degrades
gracefully, in CI, on purpose.

Every production lever in this codebase — the fused tape engine, the
serving executor, the reshard planner, checkpoint/recovery, multi-host
init — has a *fallback path* (inline eager replay, bounded retry, GSPMD
program, quarantine-and-skip, exponential backoff). The reference
framework (arXiv:2007.13552) ships no failure-testing story at all, and a
fallback that only fires when production breaks is a fallback nobody has
ever seen run. This module makes the failure paths first-class citizens:

* **Sites.** Each critical failure surface is threaded with a *named
  injection site* (:data:`SITES` is the authoritative registry — the
  chaos matrix in ``tests/test_faults.py`` enumerates it, so adding a
  site without chaos coverage fails CI). A site is one
  :func:`check` call placed exactly where the real world would throw:
  before an XLA compile, a collective dispatch, a filesystem write.
* **Plans.** A :class:`FaultPlan` maps sites to *firing rules*:
  ``nth:N`` (fire on exactly the Nth hit), ``every:N`` (every Nth hit),
  ``prob:P@SEED`` (seeded Bernoulli — deterministic across runs).
  Arm a plan with the :func:`inject` context manager, or process-wide
  via ``HEAT_TPU_FAULTS=site=rule;site2=rule`` at import time.
* **Zero disarmed overhead.** With no plan armed, every site is a module
  attribute read plus an early return (``_PLAN is None``) — no dict
  walk, no string formatting, nothing on the device. The tier-1 suite
  runs with faults disarmed and a counter-silence check pins that no
  site ever fires outside a chaos leg.
* **Counters.** Each fire increments ``faults.fires`` and
  ``faults.<site>.fires`` in :mod:`heat_tpu.utils.metrics`; each arm
  increments ``faults.arms``. :func:`stats` (surfaced as
  ``ht.runtime_stats()["faults"]``) snapshots the armed plan and
  per-site fire counts.

When a site fires it raises the **exception class the real failure
would**: filesystem sites raise ``OSError``, runtime sites raise
:class:`FaultInjected` (a ``RuntimeError``) — so the hardened paths
under test catch exactly what they would catch in production, never a
test-only type.

The failure-domain matrix (site → detection → fallback → counter →
escape hatch) lives in ``doc/robustness.md``, next to the chaos-local
runbook for the ``HEAT_TPU_FAULTS`` grammar.
"""

from __future__ import annotations

import contextlib
import os
import random
import threading
from typing import Dict, Optional

from . import metrics as _metrics

__all__ = [
    "FaultInjected",
    "FaultPlan",
    "SITES",
    "arm",
    "armed",
    "check",
    "disarm",
    "inject",
    "parse_spec",
    "site_doc",
    "stats",
]


class FaultInjected(RuntimeError):
    """The error an armed runtime site raises when its rule fires."""


# ---------------------------------------------------------------------- #
# the site registry                                                      #
# ---------------------------------------------------------------------- #
# name -> (exception class raised on fire, one-line doc used by the chaos
# matrix and doc/robustness.md). The class is what the REAL failure would
# raise at that point, so hardened except-clauses are exercised as-is.
SITES: Dict[str, tuple] = {
    # fused tape engine (core/fusion.py)
    "fusion.flush.compile": (
        FaultInjected,
        "flush program build (shard_map and plain-jit paths both route "
        "through the one build())"),
    "fusion.flush.dispatch": (
        FaultInjected,
        "compiled flush program dispatch (program(*leaves))"),
    "fusion.step.trace": (
        FaultInjected,
        "trace_step first trace/compile of a new argument signature"),
    "fusion.step.dispatch": (
        FaultInjected,
        "trace_step dispatch of a PRIMED (previously successful) program"),
    "fusion.quant.encode": (
        FaultInjected,
        "quantized-collective encode planning (flush packing and "
        "packed_psum) — falls back to the exact collective, counted in "
        "op_engine.quant_fallbacks"),
    "fusion.chunk.dispatch": (
        FaultInjected,
        "chunked packed-collective leg planning (fires once per intended "
        "chunk leg, flush plan and packed_psum) — degrades to the "
        "UNCHUNKED packed collective (for flushes via the cache key, "
        "hitting any cached unchunked program), counted in "
        "op_engine.chunk_fallbacks"),
    "fusion.hier.exchange": (
        FaultInjected,
        "tier-aware hierarchical packed-collective planning (flush plan "
        "and packed_psum) — degrades to the FLAT packed collective (for "
        "flushes via the cache key, hitting any cached flat program), "
        "counted in op_engine.hier_fallbacks"),
    "fit.step.dispatch": (
        FaultInjected,
        "compiled analytics fit-step dispatch (fusion.fit_step_call: the "
        "estimator Lloyd/Lanczos/coordinate-sweep and KNN/GaussianNB "
        "predict programs) — degrades to the eager per-op iteration with "
        "identical results, counted in op_engine.fit_step_fallbacks"),
    # reshard planner (core/resharding.py)
    "reshard.plan.build": (
        FaultInjected,
        "explicit reshard plan compile (_build_plan)"),
    "reshard.dispatch": (
        FaultInjected,
        "reshard program dispatch (fn(parray) in reshard())"),
    # serving executor (serve/executor.py)
    "serve.worker.batch": (
        FaultInjected,
        "worker batch processing OUTSIDE the dispatch try (exercises the "
        "_run backstop: futures fail, worker survives)"),
    "serve.batch.dispatch": (
        FaultInjected,
        "batch model dispatch / host fetch (bounded one-retry path)"),
    "serve.bucket.policy": (
        FaultInjected,
        "bucket policy evaluation on the coalesced row total"),
    "serve.admission.decide": (
        FaultInjected,
        "multi-tenant admission decision (serve/executor.py::_admit) — "
        "degrades that request to the legacy bounded-FIFO admission "
        "(quota/rate/breaker skipped, request still served), counted in "
        "serve.admission_fallbacks"),
    "serve.breaker.probe": (
        FaultInjected,
        "circuit-breaker consult / half-open probe admission "
        "(serve/admission.py::check_tenant) — fails OPEN (the request is "
        "admitted; the dispatch path stays the health authority), "
        "counted in serve.breaker_fallbacks"),
    "serve.decode.step": (
        FaultInjected,
        "continuous-batching decode-step dispatch "
        "(serve/decode.py::DecodeEngine._dispatch_step) — that step "
        "degrades to the eager per-slot path with every future intact, "
        "counted in serve.decode_fallbacks"),
    # distributed data engine (data/engine.py, data/streaming.py)
    "data.exchange.dispatch": (
        FaultInjected,
        "compiled data-engine exchange dispatch (data/engine.py::"
        "engine_call: the groupby/top-k/order-statistic/join programs) — "
        "degrades to the eager per-op reference path with identical "
        "results, counted in data_engine.exchange_fallbacks"),
    "data.stream.carry": (
        FaultInjected,
        "streaming carry-fold dispatch (data/streaming.py: the donated "
        "chunk-fold executables) — that chunk degrades to the eager "
        "accumulation with identical results, counted in "
        "data_engine.stream_fallbacks"),
    # shared program cache (utils/program_cache.py)
    "program_cache.compile": (
        FaultInjected,
        "AOT compile inside ProgramCache._compile (serving form)"),
    # checkpointing (utils/checkpointing.py)
    "checkpoint.manifest.write": (
        OSError, "manifest.json temp-write/replace"),
    "checkpoint.leaf.write": (
        OSError, "arrays.npz (leaf payload) temp-write/replace"),
    "checkpoint.manifest.read": (
        OSError, "manifest.json open/parse on restore"),
    "checkpoint.leaf.read": (
        OSError, "arrays.npz open/decode on restore"),
    # multi-host bring-up (core/communication.py)
    "init.coordinator.connect": (
        FaultInjected,
        "jax.distributed.initialize coordinator connect"),
}


def site_doc(site: str) -> str:
    return SITES[site][1]


# ---------------------------------------------------------------------- #
# firing rules / plans                                                   #
# ---------------------------------------------------------------------- #
class _Rule:
    """One site's firing rule plus its per-arm hit state."""

    __slots__ = ("mode", "n", "p", "seed", "hits", "_rng")

    def __init__(self, mode: str, n: int = 1, p: float = 0.0,
                 seed: int = 0):
        self.mode = mode
        self.n = int(n)
        self.p = float(p)
        self.seed = int(seed)
        self.hits = 0
        # seeded per-rule stream: same plan + same hit sequence -> same
        # fire pattern, every run (the determinism the chaos matrix pins)
        self._rng = random.Random(self.seed) if mode == "prob" else None

    def should_fire(self) -> bool:
        self.hits += 1
        if self.mode == "nth":
            return self.hits == self.n
        if self.mode == "every":
            return self.hits % self.n == 0
        return self._rng.random() < self.p  # "prob"

    def spec(self) -> str:
        if self.mode == "prob":
            return f"prob:{self.p}@{self.seed}"
        return f"{self.mode}:{self.n}"


def _parse_rule(text: str) -> _Rule:
    """``nth:N`` / ``every:N`` / ``prob:P@SEED`` / ``once`` (= nth:1)."""
    text = text.strip()
    if text in ("once", "1"):
        return _Rule("nth", 1)
    mode, _, rest = text.partition(":")
    if mode == "nth" or mode == "every":
        n = int(rest)
        if n < 1:
            raise ValueError(f"fault rule {text!r}: N must be >= 1")
        return _Rule(mode, n)
    if mode == "prob":
        p_text, _, seed_text = rest.partition("@")
        p = float(p_text)
        if not 0.0 <= p <= 1.0:
            raise ValueError(f"fault rule {text!r}: P must be in [0, 1]")
        return _Rule("prob", p=p, seed=int(seed_text or 0))
    raise ValueError(
        f"unknown fault rule {text!r} (want once | nth:N | every:N | "
        f"prob:P@SEED)")


class FaultPlan:
    """Site → firing rule map. Hit accounting lives on the plan, so one
    plan armed twice starts fresh both times (:meth:`reset`)."""

    def __init__(self, rules: Dict[str, _Rule]):
        for site in rules:
            if site not in SITES:
                raise ValueError(
                    f"unknown fault site {site!r}; registered sites: "
                    f"{sorted(SITES)}")
        self.rules = dict(rules)

    @classmethod
    def from_spec(cls, spec: str) -> "FaultPlan":
        """Parse the ``HEAT_TPU_FAULTS`` grammar:
        ``site=rule[;site=rule...]`` with rules ``once`` / ``nth:N`` /
        ``every:N`` / ``prob:P@SEED``."""
        rules: Dict[str, _Rule] = {}
        for part in spec.split(";"):
            part = part.strip()
            if not part:
                continue
            site, eq, rule = part.partition("=")
            if not eq:
                raise ValueError(
                    f"bad fault spec segment {part!r} (want site=rule)")
            rules[site.strip()] = _parse_rule(rule)
        return cls(rules)

    def reset(self) -> None:
        for r in self.rules.values():
            r.hits = 0
            if r._rng is not None:
                r._rng = random.Random(r.seed)

    def spec(self) -> Dict[str, str]:
        return {site: r.spec() for site, r in self.rules.items()}


def parse_spec(spec: str) -> FaultPlan:
    return FaultPlan.from_spec(spec)


# ---------------------------------------------------------------------- #
# arming / the hot-path check                                            #
# ---------------------------------------------------------------------- #
# the one piece of state every site reads: None = disarmed (the
# production steady state). Assignment is atomic; sites never lock.
_PLAN: Optional[FaultPlan] = None
_ARM_LOCK = threading.Lock()


def armed() -> bool:
    return _PLAN is not None


def arm(plan) -> None:
    """Activate ``plan`` (a :class:`FaultPlan`, spec string, or site→rule
    dict) process-wide; hit counters start fresh."""
    global _PLAN
    if isinstance(plan, str):
        plan = FaultPlan.from_spec(plan)
    elif isinstance(plan, dict):
        plan = FaultPlan({s: _parse_rule(r) for s, r in plan.items()})
    with _ARM_LOCK:
        plan.reset()
        _metrics.inc("faults.arms")
        _PLAN = plan


def disarm() -> None:
    global _PLAN
    with _ARM_LOCK:
        _PLAN = None


@contextlib.contextmanager
def inject(plan):
    """``with faults.inject("serve.batch.dispatch=nth:1"): ...`` — arm for
    the block, restore the previous plan (usually None) after."""
    prev = _PLAN
    arm(plan)
    try:
        yield
    finally:
        with _ARM_LOCK:
            globals()["_PLAN"] = prev


def check(site: str) -> None:
    """The instrumentation hook. Disarmed: one attribute read and out.
    Armed: consult the plan's rule for ``site`` and raise the site's
    registered exception class when it fires."""
    plan = _PLAN
    if plan is None:
        return
    rule = plan.rules.get(site)
    if rule is None or not rule.should_fire():
        return
    _metrics.inc("faults.fires")
    _metrics.inc(f"faults.{site}.fires")
    exc_cls = SITES[site][0]
    raise exc_cls(
        f"injected fault at site {site!r} (hit {rule.hits}, rule "
        f"{rule.spec()})")


def stats() -> dict:
    """Snapshot for ``ht.runtime_stats()["faults"]``: armed flag, the
    active plan's spec, and per-site fire counts (zero-fire sites are
    omitted — a fault-free run reads as an empty ``fires`` map)."""
    c = _metrics.counters()
    fires = {k[len("faults."):-len(".fires")]: int(v)
             for k, v in c.items()
             if k.startswith("faults.") and k.endswith(".fires")
             and k != "faults.fires"}
    plan = _PLAN
    return {
        "armed": plan is not None,
        "plan": plan.spec() if plan is not None else {},
        "sites": len(SITES),
        "arms": int(c.get("faults.arms", 0)),
        "total_fires": int(c.get("faults.fires", 0)),
        "fires": fires,
    }


# process-wide arming at import: the chaos ladder stage and "running
# chaos locally" both ride this (doc/robustness.md)
_env_spec = os.environ.get("HEAT_TPU_FAULTS", "").strip()
if _env_spec:
    arm(_env_spec)
del _env_spec
