"""Vision transforms (reference ``heat/utils/vision_transforms.py:12-34``
passes through torchvision.transforms). Native minimal set here — each is a
callable over jax arrays — plus a passthrough when torchvision exists."""

from __future__ import annotations

import numpy as np

import jax.numpy as jnp

__all__ = ["Compose", "Normalize", "ToTensor", "Lambda"]


class Compose:
    def __init__(self, transforms):
        self.transforms = list(transforms)

    def __call__(self, x):
        for t in self.transforms:
            x = t(x)
        return x


class Normalize:
    def __init__(self, mean, std):
        self.mean = jnp.asarray(mean)
        self.std = jnp.asarray(std)

    def __call__(self, x):
        return (x - self.mean) / self.std


class ToTensor:
    """uint8 HWC → float CHW in [0, 1]."""

    def __call__(self, x):
        x = jnp.asarray(x)
        if x.dtype == jnp.uint8:
            x = x.astype(jnp.float32) / 255.0
        if x.ndim == 3:
            x = jnp.moveaxis(x, -1, 0)
        return x


class Lambda:
    def __init__(self, fn):
        self.fn = fn

    def __call__(self, x):
        return self.fn(x)


def __getattr__(name):
    try:
        import torchvision.transforms as _tvt

        return getattr(_tvt, name)
    except ImportError:
        raise AttributeError(
            f"transform {name!r} is not in the native set and torchvision is unavailable"
        )
