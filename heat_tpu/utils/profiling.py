"""Profiling hooks.

The reference has **no tracing/profiling support** (SURVEY.md §5 —
benchmarks use bare ``time.perf_counter``). On TPU, ``jax.profiler`` traces
are nearly free, so this module exposes them first-class: TensorBoard-format
device traces, named annotation scopes, and a simple wall-time timer that
syncs properly (``block_until_ready``) so users don't time dispatch instead
of compute.
"""

from __future__ import annotations

import contextlib
import time
from typing import Any, Dict, Optional

import jax

__all__ = ["trace", "annotate", "Timer", "start_trace", "stop_trace"]


def start_trace(logdir: str) -> None:
    """Begin a device trace viewable in TensorBoard/XProf."""
    jax.profiler.start_trace(logdir)


def stop_trace() -> None:
    jax.profiler.stop_trace()


@contextlib.contextmanager
def trace(logdir: str):
    """Context manager around a device trace."""
    start_trace(logdir)
    try:
        yield
    finally:
        stop_trace()


def annotate(name: str):
    """Named scope that shows up on the trace timeline."""
    return jax.profiler.TraceAnnotation(name)


class Timer:
    """Device-synchronized wall timer.

    >>> with Timer("kmeans-epoch") as t:
    ...     result = step(x, c)
    ...     t.sync(result)
    >>> t.seconds
    """

    def __init__(self, name: str = ""):
        self.name = name
        self.seconds: Optional[float] = None
        self._sync_target = None

    def sync(self, value) -> None:
        self._sync_target = value

    def __enter__(self):
        self._t0 = time.perf_counter()
        return self

    def __exit__(self, *exc):
        if self._sync_target is not None:
            jax.block_until_ready(self._sync_target)
        self.seconds = time.perf_counter() - self._t0
        return False
