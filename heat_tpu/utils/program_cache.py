"""Shape-keyed cache of compiled XLA programs (process-wide pattern).

Lifted out of ``heat_tpu/serve/program_cache.py`` (PR 2) once a second
subsystem needed it: the serving executor caches one AOT program per
``(callable, bucket shape, dtype, mesh)`` and the op-chain fusion engine
(:mod:`heat_tpu.core.fusion`) caches one jitted program per chain
signature. Both want the same contract — a bounded key space, explicit
hit/miss/compile counters mirrored into the process-wide metrics registry
(``<name>.program_hits`` / ``_misses`` / ``_compiles``), and the
steady-state guarantee that repeat traffic triggers **zero recompiles**
(asserted in ``tests/test_serve.py`` and ``tests/test_fusion.py``).

Two entry points:

* :meth:`ProgramCache.get` — the serving form: ahead-of-time compile
  ``fn`` at one input aval (``jit(fn).lower(aval).compile()``), falling
  back to the plain ``jax.jit`` wrapper for callables that cannot lower
  from abstract values alone.
* :meth:`ProgramCache.get_custom` — the general form: the caller brings
  an arbitrary hashable key and a ``build()`` that returns the compiled
  callable; the cache contributes lookup, locking and counters. The
  fusion engine uses this (its key is a structural chain signature, and
  its build step threads donation through ``jax.jit``).
"""

from __future__ import annotations

import threading
from typing import Any, Callable, Dict, Tuple

import jax

from . import metrics as _metrics

__all__ = ["ProgramCache"]


class ProgramCache:
    """Keyed cache of compiled programs with hit/miss/compile counters."""

    # the exact key set :meth:`stats` returns — aggregators that fold many
    # caches into one counter dict (serve/metrics.py runtime_stats) init
    # from THIS tuple, so a new stats key can never KeyError them (the
    # recurring stats()-shape drift the contract test pins at the source)
    STATS_KEYS = ("hits", "misses", "compiles", "evictions", "entries")

    def __init__(self, name: str = "programs", aot: bool = True,
                 counter_prefix: str = None, max_entries: int = None):
        self.name = name
        self.aot = aot
        # mirrored-counter namespace: defaults to the cache's own name, but
        # a subsystem that aggregates many named caches under one counter
        # family can pin it (the serving executors pin "serve" so
        # ``serve.program_*`` counts every adapter's cache, as documented)
        self.counter_prefix = counter_prefix or name
        # entry cap for callers with an OPEN key space (the fusion engine:
        # leaf shapes x chain signatures). None = unbounded, correct when
        # the key space is finite by construction (the serve bucket
        # ladder). Crossing the cap clears the table (coarse, like the
        # aval memo) — counters survive, re-compiles are counted honestly.
        self.max_entries = max_entries
        self._programs: Dict[Tuple, Callable] = {}
        self._lock = threading.Lock()
        self.hits = 0
        self.misses = 0
        self.compiles = 0
        self.evictions = 0  # cap-clear events (capped caches only)

    # ------------------------------------------------------------------ #
    # generic form                                                       #
    # ------------------------------------------------------------------ #
    def get_custom(self, key, build: Callable[[], Callable]) -> Callable:
        """The program stored under ``key``, building it on first miss.

        ``build`` runs OUTSIDE the lock: a multi-second XLA compile must
        not serialize unrelated lookups. A rare double-build of the same
        key is benign (last writer wins; counters record both).
        """
        with self._lock:
            prog = self._programs.get(key)
            if prog is not None:
                self.hits += 1
                _metrics.inc(f"{self.counter_prefix}.program_hits")
                return prog
            self.misses += 1
            _metrics.inc(f"{self.counter_prefix}.program_misses")
        prog = build()
        with self._lock:
            if self.max_entries is not None and \
                    len(self._programs) >= self.max_entries:
                self._programs.clear()
                self.evictions += 1
                _metrics.inc(f"{self.counter_prefix}.program_evictions")
            self._programs[key] = prog
            self.compiles += 1
        _metrics.inc(f"{self.counter_prefix}.program_compiles")
        return prog

    # ------------------------------------------------------------------ #
    # serving form (one input aval, AOT)                                 #
    # ------------------------------------------------------------------ #
    def get(self, fn: Callable, shape: Tuple[int, ...], dtype,
            token: Any = ()) -> Callable:
        """The compiled program for ``fn`` at input aval ``(shape, dtype)``.

        ``token`` folds any extra identity into the key — executors pass
        the mesh/communicator cache key, so the same callable served over
        two meshes gets two programs.
        """
        key = (fn, tuple(int(s) for s in shape), str(dtype), token)
        return self.get_custom(key, lambda: self._compile(fn, shape, dtype))

    def _compile(self, fn, shape, dtype) -> Callable:
        from . import faults as _faults

        _faults.check("program_cache.compile")
        jitted = jax.jit(fn)
        if self.aot:
            try:
                aval = jax.ShapeDtypeStruct(tuple(shape), dtype)
                return jitted.lower(aval).compile()
            except Exception:
                # not lowerable from abstract avals (e.g. value-dependent
                # python in fn) — the jit wrapper still shape-caches
                pass
        return jitted

    def stats(self) -> dict:
        """Plain-dict counters (folded into metrics snapshots); keys are
        exactly :data:`STATS_KEYS`."""
        with self._lock:
            return {"hits": self.hits, "misses": self.misses,
                    "compiles": self.compiles,
                    "evictions": self.evictions,
                    "entries": len(self._programs)}

    def reset(self) -> None:
        with self._lock:
            self._programs.clear()
            self.hits = 0
            self.misses = 0
            self.compiles = 0
            self.evictions = 0

    def __len__(self) -> int:
        with self._lock:
            return len(self._programs)

    def __repr__(self) -> str:
        s = self.stats()
        return (f"ProgramCache({self.name!r}, entries={s['entries']}, "
                f"hits={s['hits']}, misses={s['misses']})")
