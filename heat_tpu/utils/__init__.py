"""Utility subpackages (reference ``heat/utils/``), plus checkpoint/resume
and profiling subsystems the reference lacks (SURVEY.md §5)."""

from . import data
from . import vision_transforms
from . import faults
from . import checkpointing
from . import hlo_audit
from . import metrics
from . import profiling
from .checkpointing import (
    CheckpointManager,
    checkpoint_estimator,
    load_checkpoint,
    restore_estimator,
    run_with_recovery,
    save_checkpoint,
)
