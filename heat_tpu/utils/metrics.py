"""Lightweight training/runtime metrics (beyond the reference, which has no
metrics/observability at all — SURVEY.md §5 "none beyond printing").

A process-local registry of counters, gauges and observation series with
JSON-lines export — enough to instrument training loops and benchmarks
without external dependencies:

>>> from heat_tpu.utils import metrics
>>> metrics.inc("steps")
>>> metrics.observe("loss", 0.42)
>>> with metrics.timer("epoch") as t:
...     out = train_one_epoch()
...     t.sync(out)                  # device-synced duration (optional)
>>> metrics.dump("run_metrics.jsonl", step=10)

Snapshots are sectioned (``counters`` / ``gauges`` / ``series``) so names
never collide across kinds or with ``dump``'s extra fields. ``dump``
clears the observation series by default, making each JSON line a window
since the previous dump (counters and gauges persist). Device-side values
are fetched in one batched ``jax.device_get`` per snapshot.
"""

from __future__ import annotations

import json
import math
import time
from contextlib import contextmanager
from typing import Any, Dict

__all__ = ["Metrics", "inc", "gauge", "observe", "timer", "to_dict",
           "counters", "dump", "reset"]


def _jsonable(v):
    """JSON-safe value: non-finite floats become None (strict JSON has no
    NaN/Infinity, and diverged runs are exactly when the lines must parse);
    arrays become (sanitized) nested lists; anything else unknown is
    stringified rather than aborting the dump."""
    if isinstance(v, float):
        return v if math.isfinite(v) else None
    if isinstance(v, (int, str, bool, type(None))):
        return v
    if isinstance(v, dict):
        return {k: _jsonable(x) for k, x in v.items()}
    if isinstance(v, (list, tuple)):
        return [_jsonable(x) for x in v]
    tolist = getattr(v, "tolist", None)
    if tolist is not None:
        return _jsonable(tolist())
    try:
        return _jsonable(float(v))
    except (TypeError, ValueError):
        return str(v)


class Metrics:
    """A metrics registry: counters, gauges and windowed observations."""

    def __init__(self):
        self._counters: Dict[str, float] = {}
        self._gauges: Dict[str, Any] = {}
        self._observations: Dict[str, list] = {}

    def inc(self, name: str, value: float = 1.0) -> None:
        """Add to a monotonically-increasing counter."""
        self._counters[name] = self._counters.get(name, 0.0) + value

    def gauge(self, name: str, value: Any) -> None:
        """Set a point-in-time value (kept as-is; may be a device scalar)."""
        self._gauges[name] = value

    def observe(self, name: str, value: Any) -> None:
        """Append to a value series (loss curve, step time, ...)."""
        self._observations.setdefault(name, []).append(value)

    @contextmanager
    def timer(self, name: str):
        """Record a wall-clock duration into the ``name`` series.

        Yields a :class:`heat_tpu.utils.profiling.Timer`; call its
        ``sync(value)`` on a device result inside the block so the recorded
        duration covers the compute, not just the async dispatch.
        """
        from .profiling import Timer

        with Timer(name) as t:
            yield t
        self.observe(name, t.seconds)

    def counters(self) -> Dict[str, float]:
        """Copy of the counter section only — cheap (no device fetch), so
        hot paths (serving snapshots, per-test CI hooks) can poll it."""
        return dict(self._counters)

    def to_dict(self) -> Dict[str, Any]:
        """Sectioned snapshot with per-series summary statistics."""
        # ONE batched host fetch for every device value in the snapshot
        payload = {"series": dict(self._observations), "gauges": dict(self._gauges)}
        try:
            import jax

            payload = jax.device_get(payload)
        except Exception:
            pass

        series: Dict[str, Any] = {}
        for k, vals in payload["series"].items():
            nums = []
            for v in vals:
                try:
                    f = float(v)
                except (TypeError, ValueError):
                    continue
                nums.append(f)
            if nums:
                series[k] = {
                    "count": len(nums),
                    "last": _jsonable(nums[-1]),
                    "mean": _jsonable(sum(nums) / len(nums)),
                    "min": _jsonable(min(nums)),
                    "max": _jsonable(max(nums)),
                }
            else:
                series[k] = {"count": len(vals)}
        return {
            "counters": {k: _jsonable(v) for k, v in self._counters.items()},
            "gauges": {k: _jsonable(v) for k, v in payload["gauges"].items()},
            "series": series,
        }

    def dump(self, path: str, reset_series: bool = True, **extra) -> Dict[str, Any]:
        """Append one JSON line (snapshot + ``extra`` fields) to ``path``.

        By default the observation series are cleared afterwards so each
        line summarizes the window since the previous dump — long runs
        neither grow memory nor hold device buffers alive. Counters and
        gauges persist.
        """
        record = {"ts": time.time(), **{k: _jsonable(v) for k, v in extra.items()},
                  **self.to_dict()}
        with open(path, "a") as handle:
            # allow_nan=False backstops the sanitizer: a line either parses
            # strictly or the bug surfaces here, never a silent NaN token
            handle.write(json.dumps(record, allow_nan=False) + "\n")
        if reset_series:
            self._observations.clear()
        return record

    def reset(self) -> None:
        self._counters.clear()
        self._gauges.clear()
        self._observations.clear()


_default = Metrics()


def inc(name: str, value: float = 1.0) -> None:
    _default.inc(name, value)


def gauge(name: str, value: Any) -> None:
    _default.gauge(name, value)


def observe(name: str, value: Any) -> None:
    _default.observe(name, value)


def timer(name: str):
    return _default.timer(name)


def to_dict() -> Dict[str, Any]:
    return _default.to_dict()


def counters() -> Dict[str, float]:
    return _default.counters()


def dump(path: str, reset_series: bool = True, **extra) -> Dict[str, Any]:
    return _default.dump(path, reset_series=reset_series, **extra)


def reset() -> None:
    _default.reset()
