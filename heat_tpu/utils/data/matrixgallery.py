"""Test-matrix gallery (reference ``heat/utils/data/matrixgallery.py``)."""

from __future__ import annotations

from typing import Optional

import numpy as np

import jax.numpy as jnp

from ...core import types
from ...core.communication import sanitize_comm
from ...core.dndarray import DNDarray

__all__ = ["parter"]


def parter(n: int, split: Optional[int] = None, device=None, comm=None, dtype=types.float32) -> DNDarray:
    """The Parter matrix ``A[i,j] = 1 / (i - j + 0.5)`` — a Cauchy matrix
    with singular values clustered at π (reference ``matrixgallery.py:15``)."""
    comm = sanitize_comm(comm)
    dtype = types.canonical_heat_type(dtype)
    i = jnp.arange(n, dtype=dtype.jax_type())[:, None]
    j = jnp.arange(n, dtype=dtype.jax_type())[None, :]
    a = 1.0 / (i - j + 0.5)
    from ...core import devices as _devices

    return DNDarray.from_logical(a, split, _devices.sanitize_device(device), comm, dtype=dtype)
