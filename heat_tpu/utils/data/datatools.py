"""Dataset and DataLoader (reference ``heat/utils/data/datatools.py``).

The reference's ``Dataset`` holds a DNDarray's local shard as torch data
(``datatools.py:143-245``) and the ``DataLoader`` wraps torch's with a
post-epoch global shuffle (``:16-141``, ``dataset_shuffle/ishuffle``
``:246-360``). Here the global array stays sharded on the mesh; batching is
slicing along the (sharded) sample axis, and the epoch shuffle is one
permutation applied globally (an XLA gather the partitioner turns into an
all-to-all) — same semantics, no Send/Irecv pairs.
"""

from __future__ import annotations

from typing import Iterator, List, Optional, Sequence, Union

import numpy as np

import jax
import jax.numpy as jnp

from ...core import random as ht_random
from ...core.dndarray import DNDarray

__all__ = ["DataLoader", "Dataset", "dataset_shuffle", "dataset_ishuffle"]


class Dataset:
    """Dataset over one or more DNDarrays sharing the sample axis
    (reference ``datatools.py:143``)."""

    def __init__(self, array, transforms=None, ishuffle: bool = False, test_set: bool = False):
        arrays = array if isinstance(array, (list, tuple)) else [array]
        for a in arrays:
            if not isinstance(a, DNDarray):
                raise TypeError(f"Dataset requires DNDarrays, got {type(a)}")
        n = arrays[0].shape[0]
        for a in arrays[1:]:
            if a.shape[0] != n:
                raise ValueError("all arrays must share the sample axis length")
        self.arrays = list(arrays)
        self.transforms = (
            transforms if isinstance(transforms, (list, tuple)) else
            ([transforms] * len(self.arrays) if transforms else [None] * len(self.arrays))
        )
        self.ishuffle = ishuffle
        self.test_set = test_set

    def __len__(self) -> int:
        return self.arrays[0].shape[0]

    def __getitem__(self, index):
        items = []
        for a, t in zip(self.arrays, self.transforms):
            item = a[index]
            if t is not None:
                item = t(item)
            items.append(item)
        return items[0] if len(items) == 1 else tuple(items)

    def shuffle(self):
        """Global in-place shuffle (reference ``dataset_shuffle``)."""
        dataset_shuffle(self)


class DataLoader:
    """Batched iteration with epoch-end global shuffle
    (reference ``datatools.py:16-141``).

    Yields batches as tuples of ``jax.Array`` slices of the sharded global
    arrays — each batch stays distributed over the mesh (dp axis).
    """

    def __init__(
        self,
        dataset=None,
        data=None,
        batch_size: int = 1,
        drop_last: bool = True,
        shuffle: bool = True,
        ishuffle: bool = False,
        transforms=None,
    ):
        if dataset is None:
            if data is None:
                raise TypeError("either dataset or data must be given")
            dataset = Dataset(data, transforms=transforms, ishuffle=ishuffle)
        self.dataset = dataset
        self.batch_size = int(batch_size)
        self.drop_last = drop_last
        self.shuffle = shuffle
        self.ishuffle = ishuffle
        self._last_epoch = False

    def __len__(self) -> int:
        n = len(self.dataset)
        if self.drop_last:
            return n // self.batch_size
        return -(-n // self.batch_size)

    def __iter__(self) -> Iterator:
        if self.shuffle:
            self.dataset.shuffle()
        n = len(self.dataset)
        bs = self.batch_size
        nb = len(self)
        for i in range(nb):
            lo = i * bs
            hi = min(lo + bs, n)
            batch = [a._logical()[lo:hi] for a in self.dataset.arrays]
            yield batch[0] if len(batch) == 1 else tuple(batch)


def dataset_shuffle(dataset: Dataset, attrs: Optional[List] = None) -> None:
    """Globally shuffle the sample axis of every array in the dataset
    (reference ``datatools.py:246``: pairwise Send/Irecv of shard halves;
    here the shared permutation applies through the ring-gather getitem —
    O(chunk) per device, no materialization)."""
    n = len(dataset)
    perm = np.asarray(
        ht_random.randperm(n, comm=dataset.arrays[0].comm).larray)
    for i, a in enumerate(dataset.arrays):
        if a.split is not None and a.comm.size > 1 and n > 0:
            dataset.arrays[i] = a[perm]
        else:
            shuffled = a._logical()[jnp.asarray(perm)]
            dataset.arrays[i] = DNDarray.from_logical(
                shuffled, a.split, a.device, a.comm, dtype=a.dtype)


def dataset_ishuffle(dataset: Dataset, attrs: Optional[List] = None) -> None:
    """Non-blocking shuffle (reference ``datatools.py:310``): dispatch is
    asynchronous on device by construction, so this is the same operation."""
    dataset_shuffle(dataset, attrs)
