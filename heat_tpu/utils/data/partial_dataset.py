"""Out-of-core HDF5 dataset with background prefetch
(reference ``heat/utils/data/partial_dataset.py:20-330``).

The reference trains on H5 files larger than memory by loading the next file
chunk in daemon threads through a ``queue.Queue`` while the current chunk is
training. Same design here: a prefetch thread reads the next slab from disk
and stages it to device while the current slab's batches are consumed.
"""

from __future__ import annotations

import queue
import threading
from typing import Iterator, List, Optional

import numpy as np

import jax.numpy as jnp

from ...core.communication import sanitize_comm

__all__ = ["PartialH5Dataset", "PartialH5DataLoaderIter"]


class PartialH5Dataset:
    """Chunked HDF5 streaming dataset (reference ``partial_dataset.py:20``)."""

    def __init__(
        self,
        file: str,
        comm=None,
        dataset_names: Optional[List[str]] = None,
        initial_load: int = 7000,
        load_length: int = 1000,
        use_gpu: bool = True,
        np_buffer: bool = True,
    ):
        import h5py

        self.file = file
        self.comm = sanitize_comm(comm)
        self.dataset_names = dataset_names or ["data"]
        self.initial_load = initial_load
        self.load_length = load_length
        with h5py.File(file, "r") as handle:
            self.total_size = handle[self.dataset_names[0]].shape[0]

    def __len__(self) -> int:
        return self.total_size

    def thread_replace_converted_batches(self):
        """Parity hook (reference ``partial_dataset.py:200``): chunk rotation
        happens inside the loader iterator here."""
        return None


class PartialH5DataLoaderIter:
    """Iterator that streams slabs with one prefetch thread
    (reference ``PartialH5DataLoaderIter``, ``partial_dataset.py:230-330``)."""

    def __init__(self, dataset: PartialH5Dataset, batch_size: int = 64, shuffle: bool = True, seed: int = 0):
        self.dataset = dataset
        self.batch_size = batch_size
        self.shuffle = shuffle
        self.rng = np.random.default_rng(seed)
        self._queue: "queue.Queue" = queue.Queue(maxsize=2)
        self._stop = threading.Event()
        self._thread = threading.Thread(target=self._producer, daemon=True)
        self._thread.start()

    def _producer(self):
        import h5py

        ds = self.dataset
        with h5py.File(ds.file, "r") as handle:
            handles = [handle[name] for name in ds.dataset_names]
            pos = 0
            while pos < ds.total_size and not self._stop.is_set():
                length = ds.initial_load if pos == 0 else ds.load_length
                hi = min(pos + length, ds.total_size)
                slab = [np.asarray(h[pos:hi]) for h in handles]
                self._queue.put(slab)
                pos = hi
        self._queue.put(None)

    def __iter__(self) -> Iterator:
        while True:
            slab = self._queue.get()
            if slab is None:
                break
            n = slab[0].shape[0]
            order = self.rng.permutation(n) if self.shuffle else np.arange(n)
            for lo in range(0, n - self.batch_size + 1, self.batch_size):
                idx = order[lo : lo + self.batch_size]
                batch = [jnp.asarray(s[idx]) for s in slab]
                yield batch[0] if len(batch) == 1 else tuple(batch)

    def close(self):
        self._stop.set()
