"""Standalone data-preparation utilities (parity with the reference's
``heat/utils/data/_utils.py:13-279``, which the reference itself marks as
untested, unsupported helpers).

The tfrecord index walker is pure Python (no TensorFlow needed): a TFRecord
file is a sequence of ``(u64 length, u32 crc, proto bytes, u32 crc)`` frames,
so indexing only needs ``struct``. The ImageNet tfrecord→HDF5 merger in the
reference additionally requires TensorFlow to decode the protos; that
dependency is not available here, so the merge entry point is gated.
"""

import os
import struct

__all__ = ["tfrecord_index", "dali_tfrecord2idx", "merge_files_imagenet_tfrecord"]


def tfrecord_index(path):
    """Return ``[(offset, nbytes), ...]`` for every record frame in a
    TFRecord file (the DALI index format, one frame per line)."""
    entries = []
    with open(path, "rb") as f:
        while True:
            current = f.tell()
            byte_len = f.read(8)
            if len(byte_len) == 0:
                break
            if len(byte_len) < 8:
                raise ValueError(f"{path}: truncated TFRecord length header")
            (proto_len,) = struct.unpack("<q", byte_len)
            if proto_len < 0:
                raise ValueError(f"{path}: negative TFRecord length (not a TFRecord file)")
            if len(f.read(4)) < 4:
                raise ValueError(f"{path}: truncated TFRecord length crc")
            body = f.read(proto_len)
            if len(body) < proto_len:
                raise ValueError(f"{path}: truncated TFRecord body")
            if len(f.read(4)) < 4:
                raise ValueError(f"{path}: truncated TFRecord body crc")
            entries.append((current, f.tell() - current))
    return entries


def dali_tfrecord2idx(train_dir, train_idx_dir, val_dir, val_idx_dir):
    """Write DALI-style ``offset nbytes`` index files for every TFRecord in
    ``train_dir`` / ``val_dir`` (reference ``_utils.py:13-44``)."""
    for src_dir, out_dir in ((train_dir, train_idx_dir), (val_dir, val_idx_dir)):
        os.makedirs(out_dir, exist_ok=True)
        for name in sorted(os.listdir(src_dir)):
            src = os.path.join(src_dir, name)
            if not os.path.isfile(src):
                continue
            try:
                entries = tfrecord_index(src)
            except ValueError:
                print(f"Not a valid TFRecord file: {src}")
                continue
            with open(os.path.join(out_dir, name), "w") as idx:
                for offset, nbytes in entries:
                    idx.write(f"{offset} {nbytes}\n")


def merge_files_imagenet_tfrecord(folder_name, output_folder=None):
    """Merge preprocessed ImageNet TFRecords into one HDF5 file
    (reference ``_utils.py:46-279``). Decoding the image protos requires
    TensorFlow, which is not part of this framework's dependency set."""
    raise NotImplementedError(
        "merge_files_imagenet_tfrecord requires TensorFlow to decode ImageNet "
        "protos; install tensorflow and use tfrecord_index() for the framing"
    )
