"""Standalone data-preparation utilities (parity with the reference's
``heat/utils/data/_utils.py:13-279``, which the reference itself marks as
untested, unsupported helpers).

Everything here is TensorFlow-free:

* the tfrecord index walker reads the ``(u64 length, u32 crc, proto bytes,
  u32 crc)`` frames with ``struct``;
* :func:`parse_tf_example` decodes ``tf.train.Example`` protos with a
  minimal protobuf **wire-format** parser (the Example schema is three
  tiny fixed messages — no protobuf runtime or generated classes needed);
* the ImageNet merger decodes JPEGs with Pillow instead of
  ``tf.image.decode_jpeg`` and writes the reference's exact HDF5 layout.
"""

import base64
import os
import struct

__all__ = ["tfrecord_index", "dali_tfrecord2idx", "parse_tf_example",
           "merge_files_imagenet_tfrecord"]


def tfrecord_index(path):
    """Return ``[(offset, nbytes), ...]`` for every record frame in a
    TFRecord file (the DALI index format, one frame per line)."""
    entries = []
    with open(path, "rb") as f:
        while True:
            current = f.tell()
            byte_len = f.read(8)
            if len(byte_len) == 0:
                break
            if len(byte_len) < 8:
                raise ValueError(f"{path}: truncated TFRecord length header")
            (proto_len,) = struct.unpack("<q", byte_len)
            if proto_len < 0:
                raise ValueError(f"{path}: negative TFRecord length (not a TFRecord file)")
            if len(f.read(4)) < 4:
                raise ValueError(f"{path}: truncated TFRecord length crc")
            body = f.read(proto_len)
            if len(body) < proto_len:
                raise ValueError(f"{path}: truncated TFRecord body")
            if len(f.read(4)) < 4:
                raise ValueError(f"{path}: truncated TFRecord body crc")
            entries.append((current, f.tell() - current))
    return entries


def dali_tfrecord2idx(train_dir, train_idx_dir, val_dir, val_idx_dir):
    """Write DALI-style ``offset nbytes`` index files for every TFRecord in
    ``train_dir`` / ``val_dir`` (reference ``_utils.py:13-44``)."""
    for src_dir, out_dir in ((train_dir, train_idx_dir), (val_dir, val_idx_dir)):
        os.makedirs(out_dir, exist_ok=True)
        for name in sorted(os.listdir(src_dir)):
            src = os.path.join(src_dir, name)
            if not os.path.isfile(src):
                continue
            try:
                entries = tfrecord_index(src)
            except ValueError:
                print(f"Not a valid TFRecord file: {src}")
                continue
            with open(os.path.join(out_dir, name), "w") as idx:
                for offset, nbytes in entries:
                    idx.write(f"{offset} {nbytes}\n")


# --------------------------------------------------------------------------- #
# tf.train.Example wire-format parsing (no TensorFlow, no protobuf runtime)   #
# --------------------------------------------------------------------------- #
#
# Example      { Features features = 1; }
# Features     { map<string, Feature> feature = 1; }   (map entry: key=1, value=2)
# Feature      { oneof { BytesList bytes_list = 1; FloatList float_list = 2;
#                        Int64List int64_list = 3; } }
# BytesList    { repeated bytes value = 1; }
# FloatList    { repeated float value = 1 [packed]; }
# Int64List    { repeated int64 value = 1 [packed]; }


def _varint(buf, pos):
    result = shift = 0
    while True:
        b = buf[pos]
        pos += 1
        result |= (b & 0x7F) << shift
        if not b & 0x80:
            return result, pos
        shift += 7


def _fields(buf):
    """Yield ``(field_number, wire_type, value)`` over one message body.
    Wire type 0 -> varint int, 1 -> 8 raw bytes, 2 -> bytes, 5 -> 4 raw
    bytes; groups (3/4) don't occur in the Example schema."""
    pos, end = 0, len(buf)
    while pos < end:
        tag, pos = _varint(buf, pos)
        field, wire = tag >> 3, tag & 7
        if wire == 0:
            val, pos = _varint(buf, pos)
        elif wire == 1:
            val, pos = buf[pos:pos + 8], pos + 8
        elif wire == 2:
            ln, pos = _varint(buf, pos)
            val, pos = buf[pos:pos + ln], pos + ln
        elif wire == 5:
            val, pos = buf[pos:pos + 4], pos + 4
        else:  # pragma: no cover - not produced by the Example schema
            raise ValueError(f"unsupported protobuf wire type {wire}")
        yield field, wire, val


def _parse_list(body, kind):
    """Decode a BytesList/FloatList/Int64List message body into a list."""
    out = []
    for field, wire, val in _fields(body):
        if field != 1:
            continue
        if kind == "bytes":
            out.append(val)
        elif kind == "float":
            if wire == 2:  # packed
                out.extend(struct.unpack(f"<{len(val) // 4}f", val))
            else:
                out.append(struct.unpack("<f", val)[0])
        else:  # int64
            if wire == 2:  # packed varints
                pos = 0
                while pos < len(val):
                    v, pos = _varint(val, pos)
                    out.append(v - (1 << 64) if v >= 1 << 63 else v)
            else:
                out.append(val - (1 << 64) if val >= 1 << 63 else val)
    return out


def parse_tf_example(raw):
    """Parse a serialized ``tf.train.Example`` into
    ``{name: list}`` (bytes, float or int values per feature) — the
    TensorFlow-free stand-in for ``tf.train.Example.FromString``
    (reference ``_utils.py:165``)."""
    features = {}
    for field, _wire, val in _fields(raw):
        if field != 1:  # Example.features
            continue
        for f2, _w2, entry in _fields(val):
            if f2 != 1:  # Features.feature map entry
                continue
            key, body = None, b""
            for f3, _w3, v3 in _fields(entry):
                if f3 == 1:
                    key = v3.decode("utf-8")
                elif f3 == 2:
                    body = v3
            if key is None:
                continue
            values = []
            for f4, _w4, v4 in _fields(body):  # the Feature oneof
                if f4 == 1:
                    values = _parse_list(v4, "bytes")
                elif f4 == 2:
                    values = _parse_list(v4, "float")
                elif f4 == 3:
                    values = _parse_list(v4, "int64")
            features[key] = values
    return features


def _feat(parsed, name, default=None):
    vals = parsed.get(name) or []
    if not vals:
        if default is None:
            raise IndexError(name)
        return default
    return vals[0]


def merge_files_imagenet_tfrecord(folder_name, output_folder=None):
    """Merge preprocessed ImageNet TFRecords into HDF5 files (reference
    ``_utils.py:46-279``), TensorFlow-free: record framing via
    :func:`tfrecord_index`, proto decoding via :func:`parse_tf_example`,
    JPEG decoding via Pillow. Output layout matches the reference:
    ``imagenet_merged.h5`` / ``imagenet_merged_validation.h5`` with
    ``images`` (base64 ascii of the decoded RGB bytes), ``metadata``
    (9 float columns) and ``file_info`` (4 string columns), plus the
    ``column_names`` attributes.

    (The reference's own file listing crashes — ``list.sort()`` returns
    ``None`` into ``len()`` — consistent with its "untested, unsupported"
    banner; the intent, a sorted train/val split by filename prefix, is
    implemented here.)
    """
    import io

    import h5py
    import numpy as np

    try:
        from PIL import Image
    except ImportError as exc:  # pragma: no cover - env without Pillow
        raise ImportError(
            "merge_files_imagenet_tfrecord decodes JPEGs with Pillow; "
            "install it (pip install pillow) — TensorFlow is NOT needed"
        ) from exc

    output_folder = output_folder or ""
    train_names = sorted(
        os.path.join(folder_name, f) for f in os.listdir(folder_name)
        if f.startswith("train"))
    val_names = sorted(
        os.path.join(folder_name, f) for f in os.listdir(folder_name)
        if f.startswith("val"))

    dt = h5py.string_dtype(encoding="ascii")

    def _single_file_load(src):
        imgs = []
        img_meta = [[] for _ in range(9)]
        file_arr = [[] for _ in range(4)]
        with open(src, "rb") as fh:
            for offset, nbytes in tfrecord_index(src):
                fh.seek(offset + 12)  # skip length + length-crc
                parsed = parse_tf_example(fh.read(nbytes - 16))
                img_bytes = _feat(parsed, "image/encoded")
                img = np.asarray(
                    Image.open(io.BytesIO(img_bytes)).convert("RGB"),
                    dtype=np.uint8)
                imgs.append(base64.binascii.b2a_base64(
                    img.tobytes()).decode("ascii"))
                img_meta[0].append(float(_feat(parsed, "image/height")))
                img_meta[1].append(float(_feat(parsed, "image/width")))
                img_meta[2].append(float(_feat(parsed, "image/channels")))
                img_meta[3].append(_feat(parsed, "image/class/label") - 1)
                try:
                    bbxmin = _feat(parsed, "image/object/bbox/xmin")
                    bbxmax = _feat(parsed, "image/object/bbox/xmax")
                    bbymin = _feat(parsed, "image/object/bbox/ymin")
                    bbymax = _feat(parsed, "image/object/bbox/ymax")
                    bblabel = _feat(parsed, "image/object/bbox/label") - 1
                except IndexError:
                    bbxmin, bbxmax = 0.0, img_meta[1][-1]
                    bbymin, bbymax = 0.0, img_meta[0][-1]
                    bblabel = -2
                img_meta[4].append(float(bbxmin))
                img_meta[5].append(float(bbxmax))
                img_meta[6].append(float(bbymin))
                img_meta[7].append(float(bbymax))
                img_meta[8].append(bblabel)
                file_arr[0].append(_feat(parsed, "image/format", b"JPEG"))
                file_arr[1].append(_feat(parsed, "image/filename", b""))
                file_arr[2].append(_feat(parsed, "image/class/synset", b""))
                file_arr[3].append(_feat(parsed, "image/class/text", b""))
        return (imgs, np.array(img_meta, dtype=np.float64).T,
                np.array(file_arr, dtype="S10").T)

    def _write(file, imgs, img_meta, file_arr, past):
        file["images"].resize((past + len(imgs),))
        file["images"][past:past + len(imgs)] = imgs
        file["metadata"].resize((past + img_meta.shape[0], 9))
        file["metadata"][past:past + img_meta.shape[0]] = img_meta
        file["file_info"].resize((past + file_arr.shape[0], 4))
        file["file_info"][past:past + file_arr.shape[0]] = file_arr

    def _merge(names, out_path):
        with h5py.File(out_path, "w") as f:
            f.create_dataset("images", (0,), chunks=True, maxshape=(None,),
                             dtype=dt)
            f.create_dataset("metadata", (0, 9), chunks=True,
                             maxshape=(None, 9))
            f.create_dataset("file_info", (0, 4), chunks=True,
                             maxshape=(None, 4), dtype="S10")
            past = 0
            for src in names:  # one file at a time: O(file) host memory
                imgs, img_meta, file_arr = _single_file_load(src)
                if imgs:
                    _write(f, imgs, img_meta, file_arr, past)
                    past += len(imgs)
            f["metadata"].attrs["column_names"] = [
                "image/height", "image/width", "image/channels",
                "image/class/label", "image/object/bbox/xmin",
                "image/object/bbox/xmax", "image/object/bbox/ymin",
                "image/object/bbox/ymax", "image/object/bbox/label"]
            f["file_info"].attrs["column_names"] = [
                "image/format", "image/filename", "image/class/synset",
                "image/class/text"]

    _merge(train_names, os.path.join(output_folder, "imagenet_merged.h5"))
    _merge(val_names,
           os.path.join(output_folder, "imagenet_merged_validation.h5"))
