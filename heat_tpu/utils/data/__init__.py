"""Data tools (reference ``heat/utils/data/``)."""

from .datatools import DataLoader, Dataset, dataset_ishuffle, dataset_shuffle
from .partial_dataset import PartialH5Dataset, PartialH5DataLoaderIter
from .mnist import MNISTDataset
from . import matrixgallery
from .matrixgallery import parter
