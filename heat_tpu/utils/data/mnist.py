"""MNIST dataset (reference ``heat/utils/data/mnist.py:16``).

The reference subclasses torchvision's MNIST and shards it over ranks. Here
the IDX files are parsed directly (no torchvision dependency) and the result
is a sharded :class:`~heat_tpu.utils.data.datatools.Dataset`.
"""

from __future__ import annotations

import gzip
import os
import struct
from typing import Optional

import numpy as np

from ...core import factories, types
from .datatools import Dataset

__all__ = ["MNISTDataset"]


def _read_idx(path: str) -> np.ndarray:
    opener = gzip.open if path.endswith(".gz") else open
    with opener(path, "rb") as f:
        zero, dtype_code, ndim = struct.unpack(">HBB", f.read(4))
        shape = struct.unpack(">" + "I" * ndim, f.read(4 * ndim))
        return np.frombuffer(f.read(), dtype=np.uint8).reshape(shape)


class MNISTDataset(Dataset):
    """MNIST over a split DNDarray (reference ``mnist.py:16``)."""

    FILES = {
        True: ("train-images-idx3-ubyte", "train-labels-idx1-ubyte"),
        False: ("t10k-images-idx3-ubyte", "t10k-labels-idx1-ubyte"),
    }

    def __init__(self, root: str, train: bool = True, transform=None, target_transform=None,
                 split: Optional[int] = 0, ishuffle: bool = False, test_set: bool = False):
        img_name, lbl_name = self.FILES[train]
        img_path = self._find(root, img_name)
        lbl_path = self._find(root, lbl_name)
        images = _read_idx(img_path).astype(np.float32) / 255.0
        labels = _read_idx(lbl_path).astype(np.int32)
        img = factories.array(images, dtype=types.float32, split=split)
        lbl = factories.array(labels, dtype=types.int32, split=split)
        super().__init__(
            [img, lbl],
            transforms=[transform, target_transform],
            ishuffle=ishuffle,
            test_set=test_set,
        )

    @staticmethod
    def _find(root: str, base: str) -> str:
        for cand in (base, base + ".gz", os.path.join("MNIST", "raw", base), os.path.join("MNIST", "raw", base + ".gz")):
            p = os.path.join(root, cand)
            if os.path.exists(p):
                return p
        raise FileNotFoundError(f"MNIST file {base} not found under {root}")
