"""Optimized-HLO collective parsing and memory accounting (jax-free).

Shared by ``scripts/collective_audit.py`` and the tier-1 HLO-audit tests:
given the ``compiled.as_text()`` dump of an XLA program, count the
collective instructions and sum their per-device result-shape payload
bytes — the partitioned payloads XLA actually emits, not a model.

Parsing is per-line (HLO prints one instruction per line) with ``/*...*/``
comments stripped first: long tuple results embed ``/*index=5*/`` markers
whose ``=`` defeats naive cross-line regexes (an 8-way all-to-all result is
an 8-tuple and WAS undercounted by the previous parser).

This module must stay importable without jax: the audit script's parent
process never touches the backend.
"""

from __future__ import annotations

import re
from typing import Dict

__all__ = ["collective_stats", "communicating_collective_stats",
           "total_collective_bytes", "collective_bytes", "memory_stats",
           "entry_root_shapes", "COLLECTIVES"]

COLLECTIVES = (
    "all-reduce", "all-gather", "reduce-scatter", "all-to-all",
    "collective-permute",
)

_DTYPE_BYTES = {
    "pred": 1, "s8": 1, "u8": 1, "bf16": 2, "f16": 2, "s16": 2, "u16": 2,
    "f32": 4, "s32": 4, "u32": 4, "f64": 8, "s64": 8, "u64": 8, "c64": 8,
    "c128": 16,
}

_COMMENT_RE = re.compile(r"/\*.*?\*/")
# ``%all-to-all.2 = (f32[128,80]{1,0}, ...) all-to-all(`` — result portion
# captured up to the mnemonic; ``-start``/``-done`` async halves are counted
# once via the start instruction.
_INSTR_RE = re.compile(
    r"^\s*(?:ROOT\s+)?%?[\w.\-]+\s*=\s*(.*?)\s*("
    + "|".join(COLLECTIVES) + r")(?:-start)?\(")
_SHAPE_RE = re.compile(r"(\w+)\[([\d,]*)\]")


def _result_bytes(result: str) -> int:
    """Per-device payload bytes of one instruction's result-type text —
    every ``dtype[dims]`` token summed (tuple results carry several).
    The ONE shape/dtype byte fold; ``collective_stats`` and
    ``collective_bytes`` both call it, so a dtype-table or shape-syntax
    fix can never drift between the stats and the wire audit."""
    total = 0
    for dt, dims in _SHAPE_RE.findall(result):
        n = 1
        for piece in dims.split(","):
            if piece:
                n *= int(piece)
        total += n * _DTYPE_BYTES.get(dt, 4)
    return total


def collective_stats(hlo: str) -> Dict[str, Dict[str, int]]:
    """``{kind: {"count": int, "bytes": int}}`` over an optimized-HLO dump.

    ``bytes`` sums each instruction's result-shape payload once — all
    elements of a tuple-shaped result (XLA fuses independent psums into ONE
    tuple-shaped all-reduce, and tiled all-to-alls are n-tuples).
    """
    stats = {k: {"count": 0, "bytes": 0} for k in COLLECTIVES}
    for line in hlo.splitlines():
        m = _INSTR_RE.match(_COMMENT_RE.sub("", line))
        if m is None:
            continue
        result, kind = m.groups()
        stats[kind]["count"] += 1
        stats[kind]["bytes"] += _result_bytes(result)
    return {k: v for k, v in stats.items() if v["count"]}


def total_collective_bytes(stats: Dict[str, Dict[str, int]]) -> int:
    return sum(v["bytes"] for v in stats.values())


_ONE_GROUP_RE = re.compile(r"\{([\d,\s]*)\}")


def _moves_data(line: str) -> bool:
    """Whether a collective instruction line actually communicates: at
    least one replica group has more than one participant. Identity psums
    over size-1 mesh axes lower to singleton-group all-reduces
    (``replica_groups={{0},{1},...}``) that move ZERO bytes — the
    packed-collective train-step audits must not count them, and must not
    be fooled when another jax keeps them. Thin wrapper over the ONE
    replica-group parser (:func:`_group_size`): ``None`` — no/unparsable
    annotation, or the empty all-replicas form with no ``world`` in hand —
    stays the historic conservative "communicates"."""
    size = _group_size(line, None)
    return True if size is None else size > 1


def communicating_collective_stats(hlo: str) -> Dict[str, Dict[str, int]]:
    """:func:`collective_stats` restricted to instructions that move data
    between devices (non-singleton replica groups)."""
    kept = [line for line in hlo.splitlines()
            if _INSTR_RE.match(_COMMENT_RE.sub("", line)) is not None
            and _moves_data(_COMMENT_RE.sub("", line))]
    return collective_stats("\n".join(kept))


_IOTA_GROUP_RE = re.compile(r"\[(\d+),(\d+)\]")


def _group_size(line: str, world=None):
    """Largest communicating-group participant count on one collective
    instruction line, handling every replica-group form
    :func:`_moves_data` parses: brace-of-braces ``{{0,1},{2,3}}``, flat
    ``{0,1,2,3}``, EMPTY ``{}`` (one group of ALL replicas — resolved by
    ``world``), and iota ``[G,S]<=[N]`` (``S`` participants per group,
    transposed or not — a permutation changes membership, never group
    size). ``None`` when the line carries no annotation or ``world`` is
    needed but unknown — callers fall back conservatively. Brace forms
    delegate to the ONE group-list parser (:func:`_group_list`)."""
    tag = "replica_groups="
    start = line.find(tag)
    if start < 0:
        return None
    rest = line[start + len(tag):]
    if rest.startswith("["):
        # size directly from the iota shape — valid even for the
        # transposed forms _group_list declines (it needs MEMBERSHIP)
        m = _IOTA_GROUP_RE.match(rest)
        return None if m is None else int(m.group(2))
    groups = _group_list(line, world)
    if groups is None:
        return None
    return max((len(g) for g in groups), default=None)


def _group_list(line: str, world=None):
    """ALL replica groups on one collective instruction line as a list
    of participant-id tuples, or None when unparsable: brace-of-braces
    ``{{0,1},{2,3}}``, flat ``{0,1,2,3}`` (one group), EMPTY ``{}`` (one
    group of all replicas — resolved by ``world``), and the untransposed
    iota form ``[G,S]<=[N]`` (G contiguous groups of S). The per-tier
    classifier (:func:`_tier_of`) compares these against the declared
    tier factorization's expected group sets."""
    tag = "replica_groups="
    start = line.find(tag)
    if start < 0:
        return None
    rest = line[start + len(tag):]
    if rest.startswith("["):
        m = _IOTA_GROUP_RE.match(rest)
        if m is None:
            return None
        # a transpose suffix (``[G,S]<=[N]T(1,0)``) permutes the iota —
        # the contiguous reconstruction below would be WRONG for it, so
        # those lines decline (tier "other"); the suffix sits right
        # after the closing bracket of ``<=[N]``
        close = rest.find("]", m.end())
        if close >= 0 and rest[close + 1:close + 3] == "T(":
            return None
        g, s = int(m.group(1)), int(m.group(2))
        return [tuple(range(k * s, (k + 1) * s)) for k in range(g)]
    if not rest.startswith("{"):
        return None
    depth = 0
    for j, ch in enumerate(rest):
        if ch == "{":
            depth += 1
        elif ch == "}":
            depth -= 1
            if depth == 0:
                body = rest[1:j]
                groups = _ONE_GROUP_RE.findall(body)
                if groups:
                    return [tuple(int(p) for p in g.split(",") if p.strip())
                            for g in groups]
                if not body.strip():
                    return None if world is None \
                        else [tuple(range(int(world)))]
                return [tuple(int(p) for p in body.split(",")
                              if p.strip())]
    return None


def _tier_of(groups, d: int, i: int, world: int) -> str:
    """Classify one collective's replica groups against a declared
    ``(d, i)`` tier factorization (device order dcn-major, like
    ``jax.devices()`` on a pod): ``"ici"`` = the d contiguous i-device
    host groups (the fast tier), ``"dcn"`` = the i strided d-device
    cross-host groups (the slow tier), ``"full"`` = one group spanning
    the whole mesh, ``"none"`` = singleton groups (identity collectives,
    zero wire), ``"other"`` = anything else (sub-mesh programs)."""
    gs = {tuple(g) for g in groups}
    if all(len(g) <= 1 for g in gs):
        return "none"
    if gs == {tuple(range(h * i, (h + 1) * i)) for h in range(d)}:
        return "ici"
    if gs == {tuple(range(j, world, i)) for j in range(i)}:
        return "dcn"
    if len(gs) == 1 and len(next(iter(gs))) == world:
        return "full"
    return "other"


def _dcn_wire(kind: str, rbytes: int, tier: str, d: int) -> int:
    """Modeled per-device bytes CROSSING THE SLOW (DCN) TIER for one
    collective instruction. A ``"dcn"``-tier instruction's whole ring
    wire is slow-tier traffic; an ``"ici"``/``"none"`` instruction's is
    zero; a ``"full"``-mesh (or unclassified) collective is charged the
    ring formula evaluated at group size ``d`` — the payload that must
    cross between the d host groups however the flat ring is laid out
    (for an all-reduce, ``2R(d-1)/d``: the standard hierarchical lower
    bound the tiered decomposition then beats by shrinking ``R``)."""
    if tier in ("ici", "none") or d <= 1:
        return 0
    g = d
    if kind == "all-reduce":
        return 2 * rbytes * (g - 1) // g
    if kind == "reduce-scatter":
        return rbytes * (g - 1)
    if kind in ("all-gather", "all-to-all"):
        return rbytes * (g - 1) // g
    return rbytes  # collective-permute


def collective_bytes(hlo: str, world: int = None, tiers=None) -> dict:
    """Per-collective byte accounting over an optimized-HLO dump:
    element type × result shape × communicating replica groups.

    For every collective instruction this returns the per-device
    result-shape payload bytes (tuple elements summed, like
    :func:`collective_stats`), the communicating group size ``g`` and the
    modeled per-device **ring wire bytes** — what the collective actually
    moves, which the result shape alone misstates (an all-reduce passes
    its payload twice: reduce-scatter + all-gather; an all-to-all passes
    it once):

    ==================  ======================================
    kind                wire bytes (result payload ``R``)
    ==================  ======================================
    all-reduce          ``2 · R · (g-1)/g``
    reduce-scatter      ``R · (g-1)``  (input is ``g·R``)
    all-gather          ``R · (g-1)/g``
    all-to-all          ``R · (g-1)/g``
    collective-permute  ``R``
    ==================  ======================================

    Singleton groups (``g <= 1``) move ZERO wire bytes — identity psums
    are excluded automatically, matching
    :func:`communicating_collective_stats`. ``world`` resolves the empty
    all-replicas replica-group form; lines with no parsable group fall
    back to ``world`` (or a conservative 2 when unknown). The fusion
    engine's ``op_engine.quant_bytes_saved`` counter applies these same
    formulas, so the quantized-collective audit and the runtime counters
    agree by construction (``doc/fusion.md``).

    Returns ``{"per_instruction": [{kind, result_bytes, group_size,
    wire_bytes}, ...], "by_kind": {kind: {count, result_bytes,
    wire_bytes}}, "total_result_bytes", "total_wire_bytes"}``.

    With ``tiers=(d, i)`` (a declared dcn×ici factorization of ``world``
    — the simulated 2-host mesh, or ``HEAT_TPU_MESH_TIERS`` on a real
    pod) every instruction additionally carries its ``tier``
    (``"ici"``/``"dcn"``/``"full"``/``"none"``/``"other"``, classified
    by replica-group structure — :func:`_tier_of`) and
    ``dcn_wire_bytes`` (the modeled slow-tier crossing, :func:`_dcn_wire`
    — a flat full-mesh all-reduce is charged ``2R(d-1)/d``), plus
    ``by_tier`` aggregates and ``total_dcn_wire_bytes``: the DCN column
    the hierarchical-collective acceptance audits compare flat vs
    decomposed plans on.
    """
    if tiers is not None:
        d, i = int(tiers[0]), int(tiers[1])
        if world is None:
            world = d * i
        elif d * i != int(world):
            raise ValueError(
                f"tiers {tiers} do not factor world {world}")
    per = []
    for line in hlo.splitlines():
        stripped = _COMMENT_RE.sub("", line)
        m = _INSTR_RE.match(stripped)
        if m is None:
            continue
        result, kind = m.groups()
        rbytes = _result_bytes(result)
        g = _group_size(stripped, world)
        if g is None:
            g = world if world else 2
        g = int(g)
        if g <= 1:
            wire = 0
        elif kind == "all-reduce":
            wire = 2 * rbytes * (g - 1) // g
        elif kind == "reduce-scatter":
            wire = rbytes * (g - 1)
        elif kind in ("all-gather", "all-to-all"):
            wire = rbytes * (g - 1) // g
        else:  # collective-permute: one send of the payload
            wire = rbytes
        rec = {"kind": kind, "result_bytes": rbytes,
               "group_size": g, "wire_bytes": wire}
        if tiers is not None:
            groups = _group_list(stripped, world)
            tier = ("other" if groups is None
                    else _tier_of(groups, d, i, int(world)))
            rec["tier"] = tier
            rec["dcn_wire_bytes"] = _dcn_wire(kind, rbytes, tier, d)
        per.append(rec)
    by_kind: Dict[str, Dict[str, int]] = {}
    for rec in per:
        agg = by_kind.setdefault(
            rec["kind"], {"count": 0, "result_bytes": 0, "wire_bytes": 0})
        agg["count"] += 1
        agg["result_bytes"] += rec["result_bytes"]
        agg["wire_bytes"] += rec["wire_bytes"]
    out = {"per_instruction": per, "by_kind": by_kind,
           "total_result_bytes": sum(r["result_bytes"] for r in per),
           "total_wire_bytes": sum(r["wire_bytes"] for r in per)}
    if tiers is not None:
        by_tier: Dict[str, Dict[str, int]] = {}
        for rec in per:
            agg = by_tier.setdefault(
                rec["tier"],
                {"count": 0, "wire_bytes": 0, "dcn_wire_bytes": 0})
            agg["count"] += 1
            agg["wire_bytes"] += rec["wire_bytes"]
            agg["dcn_wire_bytes"] += rec["dcn_wire_bytes"]
        out["by_tier"] = by_tier
        out["total_dcn_wire_bytes"] = sum(
            r["dcn_wire_bytes"] for r in per)
    return out


_ROOT_ASSIGN_RE = re.compile(r"^\s*ROOT\s+%?[\w.\-]+\s*=\s*")


def _result_segment(rest: str) -> str:
    """The result-type portion at the start of ``rest`` (text after the
    ``=``): either one balanced parenthesized tuple type — a depth counter,
    because TPU tiled layouts like ``f32[8]{1,0:T(8,128)}`` nest parens a
    naive ``\\([^)]*\\)`` regex would stop at — or the single type token."""
    if not rest.startswith("("):
        return rest.split("(", 1)[0]
    depth = 0
    for i, ch in enumerate(rest):
        if ch == "(":
            depth += 1
        elif ch == ")":
            depth -= 1
            if depth == 0:
                return rest[: i + 1]
    return rest


def entry_root_shapes(hlo: str):
    """``[(dtype, numel), ...]`` of the ENTRY computation's ROOT result —
    one entry per tuple element (or a single entry for a non-tuple root).

    The reduction-fusion audit uses this to assert a fused
    reduction-terminated chain materializes ONLY reduced outputs: no
    full-size elementwise intermediate may survive as a program output.
    """
    in_entry = False
    for line in hlo.splitlines():
        stripped = _COMMENT_RE.sub("", line)
        if stripped.startswith("ENTRY"):
            in_entry = True
            continue
        if not in_entry:
            continue
        m = _ROOT_ASSIGN_RE.match(stripped)
        if m is None:
            continue
        out = []
        for dt, dims in _SHAPE_RE.findall(_result_segment(stripped[m.end():])):
            n = 1
            for piece in dims.split(","):
                if piece:
                    n *= int(piece)
            out.append((dt, n))
        return out
    return []


def memory_stats(compiled) -> Dict[str, int]:
    """Per-device buffer accounting from ``compiled.memory_analysis()``.

    Fail-soft: backends without the analysis (or older jax) return ``{}``;
    callers treat memory numbers as optional evidence on top of the
    deterministic HLO counts.
    """
    try:
        ma = compiled.memory_analysis()
    except Exception:
        return {}
    if ma is None:
        return {}
    out = {}
    for name in ("temp_size_in_bytes", "argument_size_in_bytes",
                 "output_size_in_bytes", "generated_code_size_in_bytes"):
        try:
            v = getattr(ma, name)
        except AttributeError:
            continue
        if isinstance(v, int):
            out[name] = v
    return out
