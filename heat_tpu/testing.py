"""Public test harness for downstream/user test suites.

The reference ships a reusable ``TestCase`` base class
(``heat/core/tests/test_suites/basic_test.py:12-367``) that its entire suite
— and downstream users — build on: ``assert_array_equal`` validates both the
distribution (per-rank local shapes against the balanced chunk formula) and
the gathered values, and ``assert_func_equal`` is the property-style "run the
heat function for every split and compare against the NumPy implementation"
idiom (SURVEY.md §4). This module provides the same surface for heat_tpu:
distribution checks go against :meth:`TPUCommunication.chunk` logical shards
instead of MPI-rank ``larray`` shapes, and the gather is a
``jax.device_get``.

Works under plain ``unittest`` and pytest alike::

    import heat_tpu as ht
    from heat_tpu.testing import TestCase

    class TestMyOp(TestCase):
        def test_exp(self):
            self.assert_func_equal((4, 5), ht.exp, np.exp)
"""

from __future__ import annotations

import unittest
from typing import Callable, Optional, Sequence, Union

import numpy as np

from .core import factories, types
from .core.communication import get_comm
from .core.devices import get_device
from .core.dndarray import DNDarray

__all__ = ["TestCase", "assert_array_equal", "assert_func_equal",
           "assert_func_equal_for_tensor"]


def _random_array(shape, dtype=np.float32, low=-10000, high=10000,
                  rng: Optional[np.random.Generator] = None) -> np.ndarray:
    """Random NumPy array: ``randn`` for floats, ``integers`` for ints
    (the reference's generation policy, ``basic_test.py:326-367``)."""
    rng = rng or np.random.default_rng()
    dtype = np.dtype(dtype)
    if np.issubdtype(dtype, np.floating):
        return rng.standard_normal(shape).astype(dtype)
    if np.issubdtype(dtype, np.integer):
        return rng.integers(low, high, size=shape).astype(dtype)
    if np.issubdtype(dtype, np.complexfloating):
        return (rng.standard_normal(shape)
                + 1j * rng.standard_normal(shape)).astype(dtype)
    if dtype == np.bool_:
        return rng.integers(0, 2, size=shape).astype(dtype)
    raise TypeError(
        f"unsupported dtype {dtype}: expected floating, integer, complex or bool")



def _compare(actual: np.ndarray, desired: np.ndarray, err_msg: str) -> None:
    """Exact for integer/bool data; tight ULP-scaled ``allclose`` for
    float/complex (XLA's libm may differ from NumPy's by an ulp, which the
    reference never sees because both of its sides are torch).

    The ground truth is quantized to the dtype the library returned before
    comparing: heat promotes ints to float32 (the reference's torch-style
    ladder) where NumPy goes to float64, so a float64 ground truth may be
    finite where the correct float32 answer over/underflows to inf/0.
    """
    import jax.numpy as jnp

    def _kind(dt):
        # jnp.issubdtype sees extended float dtypes (bfloat16 has NumPy
        # kind 'V', which np-kind checks misclassify)
        if jnp.issubdtype(dt, jnp.complexfloating):
            return "c"
        if jnp.issubdtype(dt, jnp.floating):
            return "f"
        return dt.kind

    actual = np.asarray(actual)
    desired = np.asarray(desired)
    ak, dk = _kind(actual.dtype), _kind(desired.dtype)
    if {ak, dk} <= set("iub?"):
        np.testing.assert_array_equal(actual, desired, err_msg=err_msg)
        return
    if ak in "fc" and not (ak == "f" and dk == "c"):
        # quantize the ground truth to the returned precision — but never
        # real-cast a complex expectation (that would silently drop the
        # imaginary part and wrong-pass); a real actual vs a truly complex
        # desired must fail in the complex128 comparison below
        desired = desired.astype(actual.dtype)
    eps = float(jnp.finfo(actual.dtype).eps if ak in "fc"
                else jnp.finfo(desired.dtype).eps)
    cplx = "c" in {ak, dk}
    np.testing.assert_allclose(
        actual.astype(np.complex128 if cplx else np.float64),
        desired.astype(np.complex128 if cplx else np.float64),
        rtol=16 * eps, atol=16 * eps, err_msg=err_msg)


def assert_array_equal(heat_array: DNDarray, expected_array,
                       check_dtype: bool = True) -> None:
    """Assert a DNDarray equals a NumPy reference — distribution first.

    Checks, in order (mirroring ``basic_test.py:68-141``): the object is a
    ``DNDarray``; the global shape matches; the dtype corresponds
    (``check_dtype=False`` skips this — used by :func:`assert_func_equal`,
    whose NumPy ground truth is deliberately computed at NumPy's own
    promotion and quantized for comparison); each logical shard of a split
    array matches the balanced chunk formula AND the corresponding slice of
    the expected array; the full gather equals the expected array.
    """
    if not isinstance(heat_array, DNDarray):
        raise AssertionError(
            f"not a DNDarray: {type(heat_array)}; the public API must return "
            "wrapped distributed arrays")
    expected_array = np.asarray(expected_array)
    if tuple(heat_array.shape) != tuple(expected_array.shape):
        raise AssertionError(
            f"global shape mismatch: {tuple(heat_array.shape)} vs expected "
            f"{tuple(expected_array.shape)}")
    ht_np_dtype = types.canonical_heat_type(heat_array.dtype).char()
    if check_dtype and expected_array.dtype.kind not in "OUS":
        exp_ht = types.canonical_heat_type(expected_array.dtype)
        if types.canonical_heat_type(heat_array.dtype) is not exp_ht:
            raise AssertionError(
                f"dtype mismatch: {heat_array.dtype} vs expected "
                f"{expected_array.dtype} (heat type {exp_ht})")
    split = heat_array.split
    comm = heat_array.comm
    if split is not None and len(heat_array.shape) > 0:
        # distribution check: every device's physical rows must hold exactly
        # the chunk-formula slice of the expected array (padding rows are
        # unconstrained)
        lmap = np.asarray(heat_array.lshape_map)
        phys = np.asarray(heat_array.larray)
        c = comm.chunk_size(heat_array.shape[split])
        for rank in range(comm.size):
            offset, lshape, slices = comm.chunk(heat_array.shape, split,
                                                rank=rank)
            if tuple(lmap[rank]) != tuple(lshape):
                raise AssertionError(
                    f"rank {rank}: lshape_map row {tuple(lmap[rank])} != "
                    f"balanced chunk {tuple(lshape)} (split={split})")
            nloc = lshape[split]
            phys_slices = tuple(
                slice(rank * c, rank * c + nloc) if i == split else slice(None)
                for i in range(phys.ndim))
            _compare(phys[phys_slices], expected_array[slices],
                     f"rank {rank} shard content mismatch (split={split})")
    _compare(heat_array.numpy(), expected_array,
             f"gathered content mismatch (dtype {ht_np_dtype})")


def assert_func_equal_for_tensor(
    tensor,
    heat_func: Callable,
    numpy_func: Callable,
    heat_args: Optional[dict] = None,
    numpy_args: Optional[dict] = None,
    distributed_result: bool = True,
) -> None:
    """Run ``heat_func`` with ``split=None`` and every split axis on
    ``tensor`` and compare each result against ``numpy_func`` on the same
    data (``basic_test.py:219-307``).

    ``distributed_result=False`` marks functions whose result is replicated
    (e.g. global reductions): only the gathered value is compared, never the
    per-shard distribution.
    """
    heat_args = dict(heat_args or {})
    numpy_args = dict(numpy_args or {})
    tensor = np.asarray(tensor)
    expected = np.asarray(numpy_func(tensor, **numpy_args))

    for split in (None, *range(tensor.ndim)):
        a = factories.array(tensor, split=split)
        result = heat_func(a, **heat_args)
        if np.isscalar(result) or not isinstance(result, DNDarray):
            _compare(np.asarray(result), expected,
                     f"scalar result mismatch for split={split}")
            continue
        if distributed_result and result.split is not None:
            assert_array_equal(result, expected, check_dtype=False)
        else:
            _compare(result.numpy(), expected,
                     f"result mismatch for split={split}")


def assert_func_equal(
    shape: Union[Sequence[int], tuple],
    heat_func: Callable,
    numpy_func: Callable,
    distributed_result: bool = True,
    heat_args: Optional[dict] = None,
    numpy_args: Optional[dict] = None,
    data_types: Sequence = (np.int32, np.int64, np.float32, np.float64),
    low: int = -10000,
    high: int = 10000,
    seed: Optional[int] = None,
) -> None:
    """Property-style check: random tensors of ``shape`` for every dtype in
    ``data_types``, each run through :func:`assert_func_equal_for_tensor`
    (``basic_test.py:142-218``). ``seed`` (an addition over the reference,
    whose generation is made rank-consistent by a broadcast we don't need —
    every device sees the same host program) makes failures reproducible.
    """
    if not isinstance(shape, (tuple, list)):
        raise ValueError(f"shape must be a list or tuple, got {type(shape)}")
    rng = np.random.default_rng(seed)
    for dtype in data_types:
        tensor = _random_array(shape, dtype=dtype, low=low, high=high, rng=rng)
        assert_func_equal_for_tensor(
            tensor=tensor, heat_func=heat_func, numpy_func=numpy_func,
            heat_args=heat_args, numpy_args=numpy_args,
            distributed_result=distributed_result)


class TestCase(unittest.TestCase):
    """Drop-in base class for user test suites (``basic_test.py:12``)."""

    @property
    def comm(self):
        return get_comm()

    @property
    def device(self):
        return get_device()

    def get_rank(self) -> int:
        # process index; all devices are addressable from one host program
        return self.comm.rank

    def get_size(self) -> int:
        return self.comm.size

    def assert_array_equal(self, heat_array, expected_array,
                           check_dtype: bool = True):
        assert_array_equal(heat_array, expected_array,
                           check_dtype=check_dtype)

    def assert_func_equal(self, shape, heat_func, numpy_func, **kwargs):
        assert_func_equal(shape, heat_func, numpy_func, **kwargs)

    def assert_func_equal_for_tensor(self, tensor, heat_func, numpy_func,
                                     **kwargs):
        assert_func_equal_for_tensor(tensor, heat_func, numpy_func, **kwargs)

    def assertTrue_memory_layout(self, tensor, order):
        """Layout assertion (``basic_test.py:308``): XLA owns physical
        layout, so this validates the *logical* order attribute recorded by
        ``sanitize_memory_layout`` rather than torch strides."""
        recorded = getattr(tensor, "order", "C")
        self.assertEqual(recorded, order,
                         f"memory layout {recorded!r} != expected {order!r}")
