// Native chunked CSV parser for heat_tpu.
//
// The reference's CSV path is a pure-Python byte-offset chunked parse
// (reference heat/core/io.py:710-860: each rank seeks to its byte range,
// snaps to line boundaries, splits and floats the fields in Python). This
// is the native equivalent: the same byte-range convention — a line belongs
// to the range its first byte falls in — parsed with strtod across a thread
// pool, writing straight into a caller-provided row-major double buffer.
//
// Exported C API (ctypes-friendly, no C++ types across the boundary):
//   fastcsv_scan(path, start, end, sep, &rows, &cols) -> 0 on success
//     Count data rows whose first byte lies in [start, end) and the column
//     count of the first such row. If start > 0 the range first skips to
//     the byte after the first '\n' at/after start (chunk convention).
//   fastcsv_parse(path, start, end, sep, out, rows, cols, threads) -> rows
//     Parse the same range into out[rows*cols] (row-major). Fields that
//     fail to parse become NaN (numpy.genfromtxt semantics); short rows
//     are NaN-padded, long rows truncated. Returns rows written, or -1.
//   fastcsv_parse_alloc(path, start, end, sep, threads, &rows, &cols,
//                       &data) -> 0 on success (-1 io, -3 ragged)
//     Single-read variant: reads the file once, scans and parses from the
//     same buffer, returning a malloc'd rows*cols array the caller frees
//     with fastcsv_free.
//
// Build: g++ -O3 -march=native -shared -fPIC -pthread fastcsv.cpp -o ...

#include <cctype>
#include <cerrno>
#include <cmath>
#include <cstdio>
#include <cstdlib>
#include <cstring>
#include <thread>
#include <vector>

namespace {

struct Mapped {
    char* data = nullptr;
    long size = 0;
    FILE* f = nullptr;
    bool ok() const { return data != nullptr; }
};

// Plain read (not mmap): works on every filesystem the tests use and the
// buffer is touched exactly once per pass anyway.
Mapped read_file(const char* path) {
    Mapped m;
    m.f = std::fopen(path, "rb");
    if (!m.f) return m;
    std::fseek(m.f, 0, SEEK_END);
    m.size = std::ftell(m.f);
    std::fseek(m.f, 0, SEEK_SET);
    // +1: NUL terminator so strtod on the last field of a file without a
    // trailing newline can never read past the buffer
    m.data = static_cast<char*>(std::malloc(m.size + 1));
    if (m.data && m.size > 0 &&
        std::fread(m.data, 1, m.size, m.f) != static_cast<size_t>(m.size)) {
        std::free(m.data);
        m.data = nullptr;
    }
    if (m.data) m.data[m.size] = '\0';
    return m;
}

void release(Mapped& m) {
    if (m.data) std::free(m.data);
    if (m.f) std::fclose(m.f);
}

// Snap a chunk start to the line-ownership convention.
long snap_start(const char* d, long size, long start) {
    if (start <= 0) return 0;
    long p = start;
    while (p < size && d[p - 1] != '\n') ++p;  // byte after the first newline
    return p;
}

bool blank_line(const char* b, const char* e) {
    for (const char* p = b; p < e; ++p)
        if (!std::isspace(static_cast<unsigned char>(*p))) return false;
    return true;
}

// Count columns: separators outside the line's content don't matter; a
// trailing separator is trailing content per genfromtxt (empty field).
long count_cols(const char* b, const char* e, char sep) {
    long c = 1;
    for (const char* p = b; p < e; ++p)
        if (*p == sep) ++c;
    return c;
}

void parse_line(const char* b, const char* e, char sep, double* out, long cols) {
    const char* p = b;
    for (long c = 0; c < cols; ++c) {
        const char* fe = p;
        while (fe < e && *fe != sep) ++fe;
        if (p >= e) {
            out[c] = NAN;  // short row: NaN-pad
            continue;
        }
        char* endp = nullptr;
        errno = 0;
        double v = std::strtod(p, &endp);
        // conversion must happen AND stay inside the field: strtod skips
        // leading whitespace, so an empty/whitespace field (tab-separated
        // files!) would otherwise steal the next field's digits
        bool ok = endp != p && endp <= fe;
        for (const char* q = endp; ok && q < fe; ++q)
            ok = std::isspace(static_cast<unsigned char>(*q));
        out[c] = ok ? v : NAN;
        p = fe < e ? fe + 1 : e;
    }
}

struct Range {
    long begin, end;  // byte range, start-snapped
    long rows = 0;    // rows counted in pass 1
};

// Threaded parse of [begin, end) into out[rows*cols]; returns rows written
// or a negative error. Assumes begin is already start-snapped.
long parse_ranges(const Mapped& m, long begin, long end, char sep,
                  double* out, long rows, long cols, int threads) {
    if (threads < 1) threads = 1;
    long span = end - begin;
    if (span <= 0) return 0;
    if (threads > 1 && span / threads < (1 << 16))
        threads = static_cast<int>(span / (1 << 16)) > 0
                      ? static_cast<int>(span / (1 << 16))
                      : 1;

    // carve sub-ranges on line boundaries (same snap convention)
    std::vector<Range> ranges(threads);
    for (int t = 0; t < threads; ++t) {
        long s = begin + span * t / threads;
        long e = begin + span * (t + 1) / threads;
        ranges[t].begin = t == 0 ? begin : snap_start(m.data, m.size, s);
        ranges[t].end = t == threads - 1 ? end : snap_start(m.data, m.size, e);
        if (ranges[t].begin > ranges[t].end) ranges[t].begin = ranges[t].end;
    }

    // pass 1 (parallel): rows per sub-range
    {
        std::vector<std::thread> pool;
        for (int t = 0; t < threads; ++t)
            pool.emplace_back([&, t] {
                long p = ranges[t].begin, r = 0;
                while (p < ranges[t].end) {
                    long q = p;
                    while (q < m.size && m.data[q] != '\n') ++q;
                    if (!blank_line(m.data + p, m.data + q)) ++r;
                    p = q + 1;
                }
                ranges[t].rows = r;
            });
        for (auto& th : pool) th.join();
    }

    // prefix offsets, clamp to the caller's buffer
    std::vector<long> offset(threads + 1, 0);
    for (int t = 0; t < threads; ++t) offset[t + 1] = offset[t] + ranges[t].rows;
    if (offset[threads] > rows) return -2;  // refuse to overflow

    // pass 2 (parallel): parse into the right slice
    {
        std::vector<std::thread> pool;
        for (int t = 0; t < threads; ++t)
            pool.emplace_back([&, t] {
                long p = ranges[t].begin;
                long r = offset[t];
                while (p < ranges[t].end) {
                    long q = p;
                    while (q < m.size && m.data[q] != '\n') ++q;
                    if (!blank_line(m.data + p, m.data + q)) {
                        parse_line(m.data + p, m.data + q, sep,
                                   out + r * cols, cols);
                        ++r;
                    }
                    p = q + 1;
                }
            });
        for (auto& th : pool) th.join();
    }
    return offset[threads];
}

// Scan rows/cols in [p, end); returns 0 or -3 (ragged).
int scan_range(const Mapped& m, long p, long end, char sep,
               long* out_rows, long* out_cols) {
    long rows = 0, cols = 0;
    while (p < end) {
        long q = p;
        while (q < m.size && m.data[q] != '\n') ++q;
        if (!blank_line(m.data + p, m.data + q)) {
            long c = count_cols(m.data + p, m.data + q, sep);
            if (rows == 0) {
                cols = c;
            } else if (c != cols) {
                return -3;  // ragged: numpy.genfromtxt raises, so must we
            }
            ++rows;
        }
        p = q + 1;
    }
    *out_rows = rows;
    *out_cols = cols;
    return 0;
}

}  // namespace

extern "C" {

int fastcsv_scan(const char* path, long start, long end, char sep,
                 long* out_rows, long* out_cols) {
    Mapped m = read_file(path);
    if (!m.ok()) return -1;
    if (end < 0 || end > m.size) end = m.size;
    int rc = scan_range(m, snap_start(m.data, m.size, start), end, sep,
                        out_rows, out_cols);
    release(m);
    return rc;
}

long fastcsv_parse(const char* path, long start, long end, char sep,
                   double* out, long rows, long cols, int threads) {
    Mapped m = read_file(path);
    if (!m.ok()) return -1;
    if (end < 0 || end > m.size) end = m.size;
    long begin = snap_start(m.data, m.size, start);
    long total = parse_ranges(m, begin, end, sep, out, rows, cols, threads);
    release(m);
    return total;
}

int fastcsv_parse_alloc(const char* path, long start, long end, char sep,
                        int threads, long* out_rows, long* out_cols,
                        double** out_data) {
    Mapped m = read_file(path);
    if (!m.ok()) return -1;
    if (end < 0 || end > m.size) end = m.size;
    long begin = snap_start(m.data, m.size, start);
    long rows = 0, cols = 0;
    int rc = scan_range(m, begin, end, sep, &rows, &cols);
    if (rc != 0) {
        release(m);
        return rc;
    }
    double* out = static_cast<double*>(
        std::malloc(sizeof(double) * (rows > 0 ? rows * cols : 1)));
    if (!out) {
        release(m);
        return -1;
    }
    long total = parse_ranges(m, begin, end, sep, out, rows, cols, threads);
    release(m);
    if (total != rows) {
        std::free(out);
        return -2;
    }
    *out_rows = rows;
    *out_cols = cols;
    *out_data = out;
    return 0;
}

void fastcsv_free(double* data) { std::free(data); }

}  // extern "C"
