"""Native (C++) runtime components, loaded via ctypes.

The reference keeps its entire runtime in Python and leans on external
native libraries (SURVEY.md §2: ATen kernels + MPI). heat_tpu's compute
path is XLA/Pallas; this package holds the native pieces of the runtime
AROUND that path — currently the chunked CSV parser behind
:func:`heat_tpu.core.io.load_csv` (the reference's Python byte-offset
parse, ``heat/core/io.py:710``, as a multithreaded C++ pass).

The shared library is compiled on first use with the system ``g++``
(``-O3 -shared -fPIC -pthread``) and cached next to the sources, falling
back to ``~/.cache/heat_tpu`` when the package directory is read-only.
Everything degrades gracefully: :func:`available` returns False when no
compiler (or a failed build) and callers keep their pure-Python path.
"""

from __future__ import annotations

import ctypes
import os
import subprocess
import tempfile
from typing import Optional, Tuple

import numpy as np

__all__ = ["available", "parse_csv_chunk", "scan_csv_chunk"]

_SRC = os.path.join(os.path.dirname(__file__), "fastcsv.cpp")
_LIB_NAME = "libheat_tpu_native.so"

_lib: Optional[ctypes.CDLL] = None
_tried = False


def _candidate_dirs():
    yield os.path.dirname(__file__)
    yield os.path.join(
        os.environ.get("XDG_CACHE_HOME", os.path.expanduser("~/.cache")),
        "heat_tpu")


def _build(libdir: str) -> Optional[str]:
    os.makedirs(libdir, exist_ok=True)
    target = os.path.join(libdir, _LIB_NAME)
    if os.path.exists(target) and os.path.getmtime(target) >= os.path.getmtime(_SRC):
        return target
    # build to a temp name then rename: concurrent processes race benignly
    fd, tmp = tempfile.mkstemp(suffix=".so", dir=libdir)
    os.close(fd)
    cmd = ["g++", "-O3", "-shared", "-fPIC", "-pthread", _SRC, "-o", tmp]
    try:
        subprocess.run(cmd, check=True, capture_output=True, timeout=120)
        os.replace(tmp, target)
        return target
    except Exception:
        try:
            os.unlink(tmp)
        except OSError:
            pass
        return None


def _load() -> Optional[ctypes.CDLL]:
    global _lib, _tried
    if _tried:
        return _lib
    _tried = True
    if os.environ.get("HEAT_TPU_NATIVE") in ("0", "false", "False"):
        return None
    for libdir in _candidate_dirs():
        try:
            path = _build(libdir)
        except OSError:
            path = None
        if path:
            try:
                lib = ctypes.CDLL(path)
            except OSError:
                continue
            lib.fastcsv_scan.restype = ctypes.c_int
            lib.fastcsv_scan.argtypes = [
                ctypes.c_char_p, ctypes.c_long, ctypes.c_long, ctypes.c_char,
                ctypes.POINTER(ctypes.c_long), ctypes.POINTER(ctypes.c_long)]
            lib.fastcsv_parse.restype = ctypes.c_long
            lib.fastcsv_parse.argtypes = [
                ctypes.c_char_p, ctypes.c_long, ctypes.c_long, ctypes.c_char,
                ctypes.POINTER(ctypes.c_double), ctypes.c_long, ctypes.c_long,
                ctypes.c_int]
            lib.fastcsv_parse_alloc.restype = ctypes.c_int
            lib.fastcsv_parse_alloc.argtypes = [
                ctypes.c_char_p, ctypes.c_long, ctypes.c_long, ctypes.c_char,
                ctypes.c_int, ctypes.POINTER(ctypes.c_long),
                ctypes.POINTER(ctypes.c_long),
                ctypes.POINTER(ctypes.POINTER(ctypes.c_double))]
            lib.fastcsv_free.restype = None
            lib.fastcsv_free.argtypes = [ctypes.POINTER(ctypes.c_double)]
            _lib = lib
            return _lib
    return None


def available() -> bool:
    """True when the native library is importable (compiling it on demand)."""
    return _load() is not None


def scan_csv_chunk(path: str, start: int = 0, end: int = -1,
                   sep: str = ",") -> Tuple[int, int]:
    """(rows, cols) of the data lines whose first byte is in [start, end)."""
    lib = _load()
    if lib is None:
        raise RuntimeError("native CSV parser unavailable")
    rows = ctypes.c_long()
    cols = ctypes.c_long()
    rc = lib.fastcsv_scan(path.encode(), start, end, sep.encode()[0:1],
                          ctypes.byref(rows), ctypes.byref(cols))
    if rc == -3:
        raise ValueError(f"ragged CSV (inconsistent column counts): {path!r}")
    if rc != 0:
        raise OSError(f"fastcsv_scan failed for {path!r}")
    return rows.value, cols.value


def parse_csv_chunk(path: str, start: int = 0, end: int = -1, sep: str = ",",
                    threads: Optional[int] = None) -> np.ndarray:
    """Parse a byte range of a numeric CSV into a float64 (rows, cols) array.

    Same chunk convention as the reference's parallel CSV load: a line
    belongs to the byte range its first character falls in, so adjacent
    ranges partition the file exactly. Unparseable fields become NaN;
    ragged files raise ValueError (genfromtxt parity). Single-read: the
    file is read and scanned once in C++ (``fastcsv_parse_alloc``).
    """
    lib = _load()
    if lib is None:
        raise RuntimeError("native CSV parser unavailable")
    if threads is None:
        threads = min(os.cpu_count() or 1, 16)
    rows = ctypes.c_long()
    cols = ctypes.c_long()
    data = ctypes.POINTER(ctypes.c_double)()
    rc = lib.fastcsv_parse_alloc(
        path.encode(), start, end, sep.encode()[0:1], threads,
        ctypes.byref(rows), ctypes.byref(cols), ctypes.byref(data))
    if rc == -3:
        raise ValueError(f"ragged CSV (inconsistent column counts): {path!r}")
    if rc != 0:
        raise OSError(f"fastcsv_parse_alloc failed ({rc}) for {path!r}")
    if rows.value == 0:
        return np.empty((0, max(cols.value, 0)), np.float64)
    try:
        out = np.ctypeslib.as_array(
            data, shape=(rows.value, cols.value)).copy()
    finally:
        lib.fastcsv_free(data)
    return out
