"""``heat_tpu.data`` — the tape-compiled distributed data engine.

Relational/ordering primitives (groupby-aggregate, top-k, exact order
statistics, inner hash join) and their out-of-core streaming variants,
compiled as cached ``shard_map`` programs with statically planned
exchanges — see :mod:`heat_tpu.data.ops` for the op → collective-plan
table and ``doc/data_engine.md`` for the full contract.

``ht.percentile`` / ``ht.median`` / ``ht.quantile`` route their
distributed flat reductions through :func:`order_stats` bisection
(zero all-gather) and fall back to the merge-split sort path under
``HEAT_TPU_DATA_ENGINE=0`` or on non-translatable layouts.
"""

from . import engine, ops, streaming
from .engine import enabled, override, program_cache, reset, stats
from .ops import (GroupBy, groupby, groupby_agg, join, order_stats, topk)
from .streaming import stream_groupby, stream_quantile, stream_topk

__all__ = [
    "engine", "ops", "streaming",
    "enabled", "override", "program_cache", "reset", "stats",
    "GroupBy", "groupby", "groupby_agg", "join", "order_stats", "topk",
    "stream_groupby", "stream_quantile", "stream_topk",
]
