"""Dispatch core of the tape-compiled distributed data engine.

Every relational/ordering primitive in :mod:`heat_tpu.data` compiles to a
cached ``shard_map`` program — shard-local compute plus a statically
planned exchange (one packed all-reduce for groupby, a k-sized psum
exchange for top-k, bisection-count psum rounds for order statistics, the
static-shape all-to-all for the join partition) — and dispatches through
:func:`engine_call`, the data-engine sibling of
``fusion.fit_step_call``:

* programs live in a dedicated :class:`ProgramCache` (``data_engine.*``
  counter mirror), keyed by the caller's structural signature PLUS the
  captured ``fusion.quant_key()/chunk_key()/hier_key()`` tuples, so a
  wire-codec toggle compiles a sibling program instead of reusing one
  traced under the other wire format (the PR 9 deferred-trace
  discipline);
* the ``data.exchange.dispatch`` / ``data.stream.carry`` fault sites fire
  BEFORE the program runs (donated buffers still intact), and any
  build/dispatch failure degrades to the caller's eager reference path
  with identical results, counted in ``data_engine.exchange_fallbacks``
  (or ``data_engine.stream_fallbacks`` for the streaming carry);
* a failure after a donated input buffer was already invalidated
  re-raises — replaying from dead buffers is the PR 8 flush-fallback
  hazard.

Escape hatch: ``HEAT_TPU_DATA_ENGINE=0`` (or :func:`override`) disables
the compiled paths; every caller runs its eager reference instead and
``ht.percentile``/``ht.median`` stay on the merge-split sort path.
"""

from __future__ import annotations

import os
from contextlib import contextmanager

from ..utils import metrics
from ..utils import faults as _faults
from ..utils.program_cache import ProgramCache

__all__ = ["enabled", "override", "engine_call", "program_cache",
           "stats", "reset", "DATA_ENGINE_COUNTERS"]


def _env_on(name: str, default: str = "1") -> bool:
    return os.environ.get(name, default) not in ("", "0", "false", "False")


_ENABLED = _env_on("HEAT_TPU_DATA_ENGINE")

# every counter the engine may tick — the serve/metrics aggregation and
# the stats() snapshot init from this tuple so a missing counter reads 0
# instead of KeyError'ing a dashboard (the PR 7 stats-key drift lesson)
DATA_ENGINE_COUNTERS = (
    "data_engine.dispatches",
    "data_engine.exchange_fallbacks",
    "data_engine.stream_chunks",
    "data_engine.stream_fallbacks",
    "data_engine.groupby_calls",
    "data_engine.topk_calls",
    "data_engine.quantile_calls",
    "data_engine.join_calls",
)

_CACHE = ProgramCache("data_engine", counter_prefix="data_engine")


def enabled() -> bool:
    """True when the compiled data-engine paths are active."""
    return _ENABLED


@contextmanager
def override(flag: bool):
    """Temporarily force the engine on/off (tests; mirrors the
    ``HEAT_TPU_DATA_ENGINE`` env gate)."""
    global _ENABLED
    prev = _ENABLED
    _ENABLED = bool(flag)
    try:
        yield
    finally:
        _ENABLED = prev


def program_cache() -> ProgramCache:
    return _CACHE


def engine_call(key, build, args, eager, *, site="data.exchange.dispatch",
                fallback_counter="data_engine.exchange_fallbacks"):
    """Dispatch ONE compiled data-engine program through the cache.

    ``key`` is the caller's structural signature (physical shapes, dtypes,
    logical sizes, the communicator cache key); the full program key
    appends the captured wire-codec tuples. ``build(qk, ck, hk)`` returns
    the compiled callable and must PIN the captured tuples into any
    ``packed_psum`` it traces. ``eager(*args)`` replays the same
    mathematics without the compiled program — the degrade path of the
    ``site`` fault and of real compile/dispatch failures.
    """
    from ..core import fusion

    qk, ck, hk = fusion.quant_key(), fusion.chunk_key(), fusion.hier_key()
    full_key = ("data",) + tuple(key) + (qk, ck, hk)
    try:
        prog = _CACHE.get_custom(full_key, lambda: build(qk, ck, hk))
        _faults.check(site)
        out = prog(*args)
    except Exception:
        for a in args:
            if getattr(a, "is_deleted", lambda: False)():
                raise  # donated buffer already invalidated — no replay
        metrics.inc(fallback_counter)
        return eager(*args)
    metrics.inc("data_engine.dispatches")
    return out


def stats() -> dict:
    """Data-engine snapshot (folded into ``ht.runtime_stats()`` under the
    ``"data_engine"`` key — shape pinned by ``tests/test_stats_contract``)."""
    c = metrics.counters()
    short = {k.split(".", 1)[1]: int(c.get(k, 0))
             for k in DATA_ENGINE_COUNTERS}
    return {"enabled": _ENABLED, **short, "program_cache": _CACHE.stats()}


def reset() -> None:
    """Drop every cached program (tests: the drop-caches-at-teardown
    executable-budget discipline)."""
    _CACHE.reset()
