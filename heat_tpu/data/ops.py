"""Distributed data-engine primitives as cached ``shard_map`` programs.

Every op is shard-local compute plus ONE statically planned exchange —
no gather of the data axis, ever:

=================  ====================================================
op                 collective plan (per compiled program)
=================  ====================================================
groupby-aggregate  shard-local bucketed partial aggregation (segment
                   scatter) + ONE packed all-reduce of the per-group
                   partials (``fusion.packed_psum``; min/max ride one
                   ``lax.pmin``/``pmax``) — exactly 1 communicating
                   collective, HLO-audited
top-k              shard-local ``lax.top_k`` + a k-sized psum exchange
                   of the (p, k) candidate table — ZERO all-gathers
order statistics   shard-local sort of the monotone unsigned key
(percentile/       encoding + ``bits`` bisection-count rounds, each ONE
median/quantile)   packed psum of the per-rank counts — ZERO
                   all-gathers; converges to the exact order-statistic
                   key (the count step function jumps only at attained
                   keys), then decodes bit-exactly
hash join          hash partition (``key % p``) into static (p, cap)
                   send tables + the planner's static-shape tiled
                   ``all_to_all``, validity flags riding the merge-split
                   discipline for the data-dependent bucket sizes; a
                   second capacity-exact all_to_all compacts matches to
                   the canonical split-0 layout (ONE host sync for the
                   result length, like ``_setops``)
=================  ====================================================

Total order: all ordering ops use the ``_sort.py`` monotone key
encoding, mapped onto the UNSIGNED integer line (sign bit flip) so
bisection arithmetic never overflows — ``-inf < … < -0.0 < +0.0 < … <
+inf < NaN``, NaNs canonicalized. The eager reference paths reuse the
same device-side encode/decode helpers, so fused and eager agree
bitwise on the selected elements.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np

from ..core._compat import shard_map
from ..core._sort import _float_sort_key, _index_dtype
from ..core.dndarray import DNDarray
from ..utils import faults as _faults
from ..utils import metrics
from . import engine

__all__ = ["groupby", "GroupBy", "groupby_agg", "topk", "join",
           "order_stat_take", "order_stats"]

AGGS = ("sum", "mean", "count", "min", "max")


# ---------------------------------------------------------------------- #
# total-order key encoding (unsigned line)                               #
# ---------------------------------------------------------------------- #
def _unsigned_dtype(bits: int):
    return jnp.dtype(f"uint{bits}")


def unsigned_key(x):
    """Monotone map of ``x`` onto the unsigned integer line (total order
    with NaN last; see ``_sort._float_sort_key``). Unsigned ints pass
    through; signed ints and float keys get the sign bit flipped."""
    jdt = jnp.dtype(x.dtype)
    if jdt == jnp.bool_:
        return x.astype(jnp.uint8)
    if jnp.issubdtype(jdt, jnp.unsignedinteger):
        return x
    k = _float_sort_key(x) if jnp.issubdtype(jdt, jnp.floating) else x
    kdt = jnp.dtype(k.dtype)
    bits = kdt.itemsize * 8
    ukdt = _unsigned_dtype(bits)
    return jax.lax.bitcast_convert_type(k, ukdt) ^ ukdt.type(1 << (bits - 1))


def decode_key(uk, jdt):
    """Inverse of :func:`unsigned_key` — bit-exact back to ``jdt``."""
    jdt = jnp.dtype(jdt)
    if jdt == jnp.bool_:
        return uk.astype(jnp.bool_)
    if jnp.issubdtype(jdt, jnp.unsignedinteger):
        return uk.astype(jdt)
    ukdt = jnp.dtype(uk.dtype)
    bits = ukdt.itemsize * 8
    sdt = jnp.dtype(f"int{bits}")
    s = jax.lax.bitcast_convert_type(uk ^ ukdt.type(1 << (bits - 1)), sdt)
    if not jnp.issubdtype(jdt, jnp.floating):
        return s.astype(jdt)
    fdt = jnp.dtype(jnp.float64 if bits == 64 else jnp.float32)
    imax = jnp.asarray(jnp.iinfo(sdt).max, sdt)
    b = jnp.where(s >= 0, s, imax - s)  # self-inverse under wraparound
    return jax.lax.bitcast_convert_type(b, fdt).astype(jdt)


def _key_bits(jdt) -> int:
    jdt = jnp.dtype(jdt)
    if jnp.issubdtype(jdt, jnp.floating):
        return 64 if jdt.itemsize == 8 else 32
    return max(jdt.itemsize * 8, 8)


def _orderable(jdt) -> bool:
    jdt = jnp.dtype(jdt)
    return (jnp.issubdtype(jdt, jnp.floating)
            or (jnp.issubdtype(jdt, jnp.integer) and jdt != jnp.bool_))


def _ftype():
    return jnp.float64 if jax.config.jax_enable_x64 else jnp.float32


# ---------------------------------------------------------------------- #
# groupby-aggregate                                                      #
# ---------------------------------------------------------------------- #
def _build_groupby(kphys, kjdt, vphys, vjdt, n, G, op, comm, qk, ck, hk):
    """ONE executable: segment scatter + exactly 1 communicating
    collective (a packed psum for sum/mean/count, one pmin/pmax)."""
    from ..core import fusion

    ax = comm.axis_name
    p = comm.size
    c = kphys[0] // p
    idt = _index_dtype()
    ft = _ftype()
    tail = vphys[1:] if vphys is not None else ()
    vnd = 1 + len(tail)

    def body(kb, *vbs):
        me = jax.lax.axis_index(ax)
        gpos = me.astype(idt) * c + jnp.arange(c, dtype=idt)
        valid = (gpos < n) & (kb >= 0) & (kb < G)
        idx = jnp.where(valid, kb, 0).astype(idt)
        if op == "count":
            part = jnp.zeros((G,), idt).at[idx].add(valid.astype(idt))
            (tot,) = fusion.packed_psum((part,), (ax,), quant=qk,
                                        chunks=ck, hier=hk)
            return tot
        vb = vbs[0]
        vmask = valid.reshape(valid.shape + (1,) * (vb.ndim - 1))
        gshape = (G,) + vb.shape[1:]
        if op == "sum":
            contrib = jnp.where(vmask, vb, jnp.zeros((), vb.dtype))
            part = jnp.zeros(gshape, vb.dtype).at[idx].add(contrib)
            (tot,) = fusion.packed_psum((part,), (ax,), quant=qk,
                                        chunks=ck, hier=hk)
            return tot
        if op == "mean":
            # sums AND counts accumulate in ftype: one dtype group ->
            # the packed psum stays ONE all-reduce (counts are exact
            # integers in f64 under the repo's x64 default)
            vs = jnp.where(vmask, vb, jnp.zeros((), vb.dtype)).astype(ft)
            part = jnp.zeros(gshape, ft).at[idx].add(vs)
            cnt = jnp.zeros((G,), ft).at[idx].add(valid.astype(ft))
            tot, cn = fusion.packed_psum((part, cnt), (ax,), quant=qk,
                                         chunks=ck, hier=hk)
            cn = cn.reshape((G,) + (1,) * len(tail))
            return tot / cn  # empty group -> NaN (0/0), documented
        # min / max: neutral-filled scatter + ONE pmin/pmax all-reduce
        if jnp.issubdtype(jnp.dtype(vjdt), jnp.floating):
            neutral = jnp.asarray(jnp.inf if op == "min" else -jnp.inf,
                                  vjdt)
        else:
            info = jnp.iinfo(jnp.dtype(vjdt))
            neutral = jnp.asarray(info.max if op == "min" else info.min,
                                  vjdt)
        contrib = jnp.where(vmask, vb, neutral)
        buf = jnp.full(gshape, neutral, vjdt)
        part = (buf.at[idx].min(contrib) if op == "min"
                else buf.at[idx].max(contrib))
        return (jax.lax.pmin(part, ax) if op == "min"
                else jax.lax.pmax(part, ax))

    in_specs = (comm.spec(1, 0),)
    out_nd = 1 if op == "count" else vnd
    if op != "count":
        in_specs = in_specs + (comm.spec(vnd, 0),)
    return jax.jit(shard_map(
        body, mesh=comm.mesh, in_specs=in_specs,
        out_specs=comm.spec(out_nd, None), check_vma=False))


def _eager_groupby(kphys, vphys, n, G, op):
    """Same mathematics, eagerly on the logical arrays (GSPMD eager
    ops) — the degrade path and the property-test reference."""
    kg = kphys[:n]
    idt = _index_dtype()
    ft = _ftype()
    valid = (kg >= 0) & (kg < G)
    idx = jnp.where(valid, kg, 0).astype(idt)
    if op == "count":
        return jnp.zeros((G,), idt).at[idx].add(valid.astype(idt))
    vg = vphys[:n]
    vmask = valid.reshape(valid.shape + (1,) * (vg.ndim - 1))
    gshape = (G,) + vg.shape[1:]
    if op == "sum":
        contrib = jnp.where(vmask, vg, jnp.zeros((), vg.dtype))
        return jnp.zeros(gshape, vg.dtype).at[idx].add(contrib)
    if op == "mean":
        vs = jnp.where(vmask, vg, jnp.zeros((), vg.dtype)).astype(ft)
        tot = jnp.zeros(gshape, ft).at[idx].add(vs)
        cn = jnp.zeros((G,), ft).at[idx].add(valid.astype(ft))
        return tot / cn.reshape((G,) + (1,) * (vg.ndim - 1))
    vjdt = jnp.dtype(vg.dtype)
    if jnp.issubdtype(vjdt, jnp.floating):
        neutral = jnp.asarray(jnp.inf if op == "min" else -jnp.inf, vjdt)
    else:
        info = jnp.iinfo(vjdt)
        neutral = jnp.asarray(info.max if op == "min" else info.min, vjdt)
    contrib = jnp.where(vmask, vg, neutral)
    buf = jnp.full(gshape, neutral, vjdt)
    return (buf.at[idx].min(contrib) if op == "min"
            else buf.at[idx].max(contrib))


def groupby_agg(keys: DNDarray, num_groups: int, op: str,
                values: DNDarray = None) -> DNDarray:
    """Distributed groupby-aggregate: ``keys`` (1-D integer, values in
    ``[0, num_groups)``; out-of-range rows are dropped) bucket ``values``
    (1-D or 2-D, row-aligned) into a REPLICATED ``(num_groups, ...)``
    result. Empty groups: sum/count 0, mean NaN, min/max the identity
    (±inf / integer extreme). ``mean`` returns the accumulation float
    dtype (f64 under x64)."""
    if op not in AGGS:
        raise ValueError(f"unknown groupby aggregation {op!r}")
    if keys.ndim != 1:
        raise ValueError("groupby keys must be 1-D")
    if not jnp.issubdtype(jnp.dtype(keys.larray.dtype), jnp.integer):
        raise TypeError("groupby keys must be integers")
    G = int(num_groups)
    if G <= 0:
        raise ValueError("num_groups must be positive")
    n = int(keys.shape[0])
    if op != "count":
        if values is None:
            raise ValueError(f"groupby agg {op!r} needs values")
        if values.ndim not in (1, 2) or int(values.shape[0]) != n:
            raise ValueError("groupby values must be (n,) or (n, d) "
                             "row-aligned with keys")
        if values.split != keys.split:
            values = values.resplit(keys.split)
    metrics.inc("data_engine.groupby_calls")
    comm = keys.comm
    kjdt = jnp.dtype(keys.larray.dtype)
    vjdt = jnp.dtype(values.larray.dtype) if values is not None else None
    vphys = tuple(values.larray.shape) if values is not None else None
    args = (keys.larray,) + ((values.larray,) if values is not None else ())

    def eager(kp, *vp):
        return _eager_groupby(kp, vp[0] if vp else None, n, G, op)

    if engine.enabled() and keys.split == 0:
        key = ("data.groupby", tuple(keys.larray.shape), str(kjdt),
               vphys, str(vjdt), n, G, op, comm.cache_key)
        res = engine.engine_call(
            key,
            lambda qk, ck, hk: _build_groupby(
                tuple(keys.larray.shape), kjdt, vphys, vjdt, n, G, op,
                comm, qk, ck, hk),
            args, eager)
    else:
        res = eager(*args)
    return DNDarray.from_logical(res, None, keys.device, comm)


class GroupBy:
    """``groupby(keys, num_groups)`` handle — ``.agg(op, values)`` plus
    the named shorthands."""

    def __init__(self, keys: DNDarray, num_groups: int):
        self.keys = keys
        self.num_groups = int(num_groups)

    def agg(self, op: str, values: DNDarray = None) -> DNDarray:
        return groupby_agg(self.keys, self.num_groups, op, values)

    def sum(self, values):
        return self.agg("sum", values)

    def mean(self, values):
        return self.agg("mean", values)

    def count(self):
        return self.agg("count")

    def min(self, values):
        return self.agg("min", values)

    def max(self, values):
        return self.agg("max", values)


def groupby(keys: DNDarray, num_groups: int) -> GroupBy:
    return GroupBy(keys, num_groups)


# ---------------------------------------------------------------------- #
# top-k                                                                  #
# ---------------------------------------------------------------------- #
def _build_topk(phys, jdt, n, k, largest, comm):
    """Shard-local ``lax.top_k`` + the k-sized psum exchange of the
    (p, k) candidate table — zero all-gathers of the data axis."""
    from ..core import fusion

    ax = comm.axis_name
    p = comm.size
    c = phys[0] // p
    idt = _index_dtype()

    def body(xb):
        me = jax.lax.axis_index(ax)
        gpos = me.astype(idt) * c + jnp.arange(c, dtype=idt)
        valid = gpos < n
        uk = unsigned_key(xb)
        sel = jnp.where(valid, uk if largest else ~uk,
                        jnp.zeros((), uk.dtype))
        sv, si = jax.lax.top_k(sel, k)
        # padding sits at the tail of the shard, so stable top_k never
        # displaces a valid zero-key element; invalid picks get pos=n
        # and sort after every valid candidate in the merge
        cpos = jnp.where(valid[si], gpos[si], jnp.asarray(n, idt))
        bs = jnp.zeros((p, k), sel.dtype).at[me].set(sv)
        bp = jnp.zeros((p, k), idt).at[me].set(cpos)
        bs, bp = fusion.packed_psum((bs, bp), (ax,))
        fs, fp = bs.reshape(p * k), bp.reshape(p * k)
        order = jnp.lexsort((fp, ~fs))[:k]  # sel desc, position asc
        osel, opos = fs[order], fp[order]
        ouk = osel if largest else ~osel
        return decode_key(ouk, jdt), opos

    return jax.jit(shard_map(
        body, mesh=comm.mesh, in_specs=(comm.spec(1, 0),),
        out_specs=(comm.spec(1, None), comm.spec(1, None)),
        check_vma=False))


def _eager_topk(xp, n, k, largest):
    full = xp[:n]
    idt = _index_dtype()
    uk = unsigned_key(full)
    sel = uk if largest else ~uk
    order = jnp.lexsort((jnp.arange(n, dtype=idt), ~sel))[:k]
    return full[order], order.astype(idt)


def topk(x: DNDarray, k: int, largest: bool = True):
    """Top-k of a 1-D array under the engine's total order (NaN sorts
    greatest, after +inf). Returns REPLICATED ``(values, indices)``,
    ordered by (value, then position): the exact rows ``lax.top_k`` on
    the gathered array would pick — without gathering it."""
    if x.ndim != 1:
        raise ValueError("topk expects a 1-D array")
    n = int(x.shape[0])
    k = int(k)
    if not 1 <= k <= n:
        raise ValueError(f"k={k} out of range for {n} elements")
    if not _orderable(x.larray.dtype):
        raise TypeError(f"topk: unordered dtype {x.dtype}")
    metrics.inc("data_engine.topk_calls")
    comm = x.comm
    jdt = jnp.dtype(x.larray.dtype)
    c = x.larray.shape[0] // comm.size if x.split == 0 else 0

    def eager(xp):
        return _eager_topk(xp, n, k, largest)

    if engine.enabled() and x.split == 0 and k <= c:
        key = ("data.topk", tuple(x.larray.shape), str(jdt), n, k,
               bool(largest), comm.cache_key)
        vals, pos = engine.engine_call(
            key,
            lambda qk, ck, hk: _build_topk(
                tuple(x.larray.shape), jdt, n, k, largest, comm),
            (x.larray,), eager)
    else:
        vals, pos = eager(x.larray)
    return (DNDarray.from_logical(vals, None, x.device, comm),
            DNDarray.from_logical(pos, None, x.device, comm))


# ---------------------------------------------------------------------- #
# order statistics (percentile / median / quantile)                      #
# ---------------------------------------------------------------------- #
def _build_order_stats(phys, jdt, split, gshape, m, comm):
    """Bisection on the unsigned key line: shard-local sort once, then
    ``bits`` rounds of (searchsorted count -> ONE packed psum) converge
    every requested rank to its exact order-statistic key — zero
    all-gathers, all-reduce payload is the (m,) count vector."""
    ax = comm.axis_name
    p = comm.size
    c = phys[split] // p
    idt = _index_dtype()
    bits = _key_bits(jdt)
    ukdt = _unsigned_dtype(bits)
    umax = np.asarray(np.iinfo(np.dtype(f"uint{bits}")).max, ukdt)

    def body(xb, rk):
        me = jax.lax.axis_index(ax)
        pos_s = me.astype(idt) * c + jnp.arange(c, dtype=idt)
        valid_s = pos_s < gshape[split]
        shape = [1] * xb.ndim
        shape[split] = c
        mask = jnp.broadcast_to(valid_s.reshape(shape), xb.shape).ravel()
        uk = unsigned_key(xb).ravel()
        # padding keys to umax: umax is unattained for floats (the
        # canonical-NaN key sits strictly below it); for ints an
        # attained umax still converges correctly — the minimal key v
        # with count(<=v) >= r+1 is unaffected below umax, and at umax
        # the (inflated) count only confirms an answer that is umax
        su = jnp.sort(jnp.where(mask, uk, umax))
        lo = jnp.zeros((m,), ukdt)
        hi = jnp.full((m,), umax, ukdt)

        def rnd(_, carry):
            lo, hi = carry
            done = lo >= hi
            mid = lo + (hi - lo) // jnp.asarray(2, ukdt)
            cnt = jnp.searchsorted(su, mid, side="right").astype(idt)
            cnt = jax.lax.psum(cnt, ax)
            ge = cnt >= rk + 1
            nlo = jnp.where(ge, lo, mid + jnp.asarray(1, ukdt))
            nhi = jnp.where(ge, mid, hi)
            return (jnp.where(done, lo, nlo), jnp.where(done, hi, nhi))

        lo, hi = jax.lax.fori_loop(0, bits, rnd, (lo, hi))
        return decode_key(lo, jdt)

    return jax.jit(shard_map(
        body, mesh=comm.mesh,
        in_specs=(comm.spec(len(phys), split), comm.spec(1, None)),
        out_specs=comm.spec(1, None), check_vma=False))


def order_stats(x: DNDarray, ranks) -> jnp.ndarray:
    """Exact order statistics of the flattened distributed bag at the
    given (sorted, 0-based) ranks, under the engine's total order —
    REPLICATED (m,) values in ``x``'s dtype, no gather of the data."""
    ranks_t = tuple(int(r) for r in ranks)
    metrics.inc("data_engine.quantile_calls")
    comm = x.comm
    jdt = jnp.dtype(x.larray.dtype)
    m = len(ranks_t)
    idt = _index_dtype()
    args = (x.larray, jnp.asarray(ranks_t, dtype=idt))

    def eager(xp, rk):
        uk = unsigned_key(x._logical().ravel())
        return decode_key(jnp.sort(uk)[rk], jdt)

    key = ("data.ostats", tuple(x.larray.shape), str(jdt), x.split,
           tuple(int(s) for s in x.shape), m, comm.cache_key)
    return engine.engine_call(
        key,
        lambda qk, ck, hk: _build_order_stats(
            tuple(x.larray.shape), jdt, x.split,
            tuple(int(s) for s in x.shape), m, comm),
        args, eager)


def order_stat_take(x: DNDarray, n: int, q_arr, interpolation: str,
                    floating: bool):
    """Engine route for ``statistics._percentile_distributed``'s flat
    branch: precompute the needed ranks, run ONE bisection program, and
    return a ``take(i)`` closure — or None when the engine is off or the
    layout/dtype is not translatable (the caller falls back to the
    merge-split sort path)."""
    if not engine.enabled() or n <= 0 or x.split is None:
        return None
    if not _orderable(x.larray.dtype):
        return None
    ranks = set()
    for qv in np.asarray(q_arr, dtype=np.float64).reshape(-1):
        f = (n - 1) * float(qv) / 100.0
        lo, hi = int(np.floor(f)), int(np.ceil(f))
        if interpolation == "lower":
            ranks.add(lo)
        elif interpolation == "higher":
            ranks.add(hi)
        elif interpolation == "nearest":
            ranks.add(int(np.round(f)))
        else:  # linear / midpoint interpolate between both neighbours
            ranks.update((lo, hi))
    if floating:
        ranks.add(n - 1)  # the NaN-poisoning probe
    ranks_t = tuple(sorted(ranks))
    vals = order_stats(x, ranks_t)
    index = {r: i for i, r in enumerate(ranks_t)}
    return lambda i: vals[index[int(i)]]


# ---------------------------------------------------------------------- #
# hash join (inner, integer keys)                                        #
# ---------------------------------------------------------------------- #
def _build_join_probe(lphys, lkdt, lvdt, rphys, rkdt, rvdt, n_l, n_r,
                      comm):
    """Phase A: hash-partition both sides with the static-shape tiled
    all_to_all (capacity = the local chunk, validity flags riding the
    merge-split discipline), then probe the sorted right bucket."""
    ax = comm.axis_name
    p = comm.size
    cl = lphys[0] // p
    cr = rphys[0] // p
    idt = _index_dtype()

    def partition(keys, vals, cn, n_side, me):
        gpos = me.astype(idt) * cn + jnp.arange(cn, dtype=idt)
        valid = (gpos < n_side) & (keys >= 0)
        dest = jnp.where(valid, keys % p, p).astype(idt)
        order = jnp.argsort(dest, stable=True)
        sd = dest[order]
        start = jnp.searchsorted(sd, sd, side="left")
        slot = jnp.arange(cn, dtype=idt) - start.astype(idt)
        flat = sd * cn + slot  # dest==p rows land past the buffer: drop
        sk = jnp.full((p * cn,), -1, keys.dtype).at[flat].set(
            keys[order], mode="drop")
        sv = jnp.zeros((p * cn,), vals.dtype).at[flat].set(
            vals[order], mode="drop")
        rk = jax.lax.all_to_all(sk.reshape(p, cn), ax, 0, 0, tiled=True)
        rv = jax.lax.all_to_all(sv.reshape(p, cn), ax, 0, 0, tiled=True)
        return rk.reshape(p * cn), rv.reshape(p * cn)

    def body(lk, lv, rk, rv):
        me = jax.lax.axis_index(ax)
        lbk, lbv = partition(lk, lv, cl, n_l, me)
        rbk, rbv = partition(rk, rv, cr, n_r, me)
        ordr = jnp.argsort(rbk)  # invalid (-1) sorts first
        srk, srv = rbk[ordr], rbv[ordr]
        idx = jnp.searchsorted(srk, lbk, side="left")
        idxc = jnp.minimum(idx, p * cr - 1)
        found = (idx < p * cr) & (srk[idxc] == lbk) & (lbk >= 0)
        mrv = srv[idxc]
        fm = found.astype(idt)
        cnt = jnp.sum(fm)
        off = comm.exscan(cnt)
        total = jax.lax.psum(cnt, ax)
        pos = off + jnp.cumsum(fm) - fm
        outpos = jnp.where(found, pos, -1)
        return found, outpos, lbk, lbv, mrv, total

    return jax.jit(shard_map(
        body, mesh=comm.mesh, in_specs=(comm.spec(1, 0),) * 4,
        out_specs=(comm.spec(1, 0),) * 5 + (comm.spec(0, None),),
        check_vma=False))


def _build_join_compact(bphys, kdt, lvdt, rvdt, M, comm):
    """Phase B (keyed by the host-synced match count M): route every
    matched row to its canonical split-0 slot with a capacity-EXACT
    all_to_all (output positions are unique and contiguous)."""
    ax = comm.axis_name
    p = comm.size
    c_out = comm.chunk_size(M)
    idt = _index_dtype()

    def body(match, outpos, kk, lv, rv):
        dest = jnp.where(match, outpos // c_out, p).astype(idt)
        slot = jnp.where(match, outpos % c_out, 0).astype(idt)
        flat = dest * c_out + slot  # invalid rows land past the buffer

        def route(vals):
            s = jnp.zeros((p * c_out,), vals.dtype).at[flat].set(
                vals, mode="drop")
            r = jax.lax.all_to_all(s.reshape(p, c_out), ax, 0, 0,
                                   tiled=True)
            return r.sum(axis=0)  # exactly one writer per slot

        return route(kk), route(lv), route(rv)

    return jax.jit(shard_map(
        body, mesh=comm.mesh, in_specs=(comm.spec(1, 0),) * 5,
        out_specs=(comm.spec(1, 0),) * 3, check_vma=False))


def _eager_join(lk, lv, rk, rv, n_l, n_r, p):
    """Host-side reference with the compiled path's output order:
    matched left rows sorted by (key % p, original position)."""
    lk = np.asarray(lk[:n_l])
    lv = np.asarray(lv[:n_l])
    rk = np.asarray(rk[:n_r])
    rv = np.asarray(rv[:n_r])
    ordr = np.argsort(rk, kind="stable")
    srk, srv = rk[ordr], rv[ordr]
    idx = np.searchsorted(srk, lk, side="left")
    idxc = np.minimum(idx, max(n_r - 1, 0))
    found = (idx < n_r) & (srk[idxc] == lk) & (lk >= 0)
    order = np.lexsort((np.arange(n_l), lk % p))
    sel = order[found[order]]
    return lk[sel], lv[sel], srv[idxc][sel]


def join(left_keys: DNDarray, left_values: DNDarray,
         right_keys: DNDarray, right_values: DNDarray):
    """Distributed inner hash join on NON-NEGATIVE integer keys (the
    right side is the build side and its keys must be unique — duplicate
    right keys give an unspecified pick). Returns split-0
    ``(keys, left_values, right_values)`` of the matched rows, ordered
    by (key % p, left position); ONE host sync fixes the result length.
    """
    for a, nd in ((left_keys, 1), (left_values, 1), (right_keys, 1),
                  (right_values, 1)):
        if a.ndim != nd:
            raise ValueError("join expects 1-D keys and 1-D values")
    for kk in (left_keys, right_keys):
        if not jnp.issubdtype(jnp.dtype(kk.larray.dtype), jnp.signedinteger):
            raise TypeError("join keys must be signed integers")
    n_l, n_r = int(left_keys.shape[0]), int(right_keys.shape[0])
    if int(left_values.shape[0]) != n_l or int(right_values.shape[0]) != n_r:
        raise ValueError("join values must be row-aligned with their keys")
    metrics.inc("data_engine.join_calls")
    comm = left_keys.comm
    p = comm.size
    device = left_keys.device
    args = (left_keys.larray, left_values.larray,
            right_keys.larray, right_values.larray)

    def _wrap(kk, lv, rv, split):
        return (DNDarray.from_logical(kk, split, device, comm),
                DNDarray.from_logical(lv, split, device, comm),
                DNDarray.from_logical(rv, split, device, comm))

    translatable = (engine.enabled()
                    and left_keys.split == 0 and left_values.split == 0
                    and right_keys.split == 0 and right_values.split == 0)
    if translatable:
        cache = engine.program_cache()
        lkdt, lvdt = (jnp.dtype(a.dtype) for a in args[:2])
        rkdt, rvdt = (jnp.dtype(a.dtype) for a in args[2:])
        sig = (tuple(args[0].shape), str(lkdt), str(lvdt),
               tuple(args[2].shape), str(rkdt), str(rvdt), n_l, n_r,
               comm.cache_key)
        try:
            _faults.check("data.exchange.dispatch")
            prog_a = cache.get_custom(
                ("data.join.a",) + sig,
                lambda: _build_join_probe(
                    tuple(args[0].shape), lkdt, lvdt,
                    tuple(args[2].shape), rkdt, rvdt, n_l, n_r, comm))
            match, outpos, bk, bv, mrv, total = prog_a(*args)
            M = int(total)  # the ONE host sync (the _setops discipline)
            if M == 0:
                empty = _wrap(jnp.zeros((0,), lkdt), jnp.zeros((0,), lvdt),
                              jnp.zeros((0,), rvdt), 0)
            else:
                prog_b = cache.get_custom(
                    ("data.join.b",) + sig + (M,),
                    lambda: _build_join_compact(
                        tuple(bk.shape), lkdt, lvdt, rvdt, M, comm))
                gk, gl, gr = prog_b(match, outpos, bk, bv, mrv)
                empty = _wrap(gk[:M], gl[:M], gr[:M], 0)
        except Exception:
            metrics.inc("data_engine.exchange_fallbacks")
            kk, lv, rv = _eager_join(*args, n_l=n_l, n_r=n_r, p=p)
            return _wrap(jnp.asarray(kk), jnp.asarray(lv),
                         jnp.asarray(rv), 0)
        metrics.inc("data_engine.dispatches")
        return empty
    kk, lv, rv = _eager_join(*args, n_l=n_l, n_r=n_r, p=p)
    return _wrap(jnp.asarray(kk), jnp.asarray(lv), jnp.asarray(rv), 0)
