"""Streaming data-engine ops: fold ``DataStream`` chunks through ONE
donated carry-state executable.

Memory contract: the resident set is ONE chunk plus a tiny carry —
``(p, G)`` group partials, ``(p, k)`` top-k candidates, or a
``(p, m·branch)`` bisection count table — so a 100M-row dataset never
materializes. Each chunk shape compiles at most one step program (the
tail chunk adds a second); the carry buffers are DONATED, so XLA updates
them in place and steady-state chunk folding neither recompiles nor
grows device memory.

Collective plan: chunk folding is shard-LOCAL (zero collectives per
chunk — every device accumulates its shard rows into its own carry row);
the cross-device combine happens ONCE at finalize, on the host, over the
``(p, …)`` carry (a p-row fetch, not a data gather).

Quantiles run multi-pass ``branch``-way bisection: each pass counts
``uk <= pivot`` for a grid of ``branch`` pivots per rank (a shard-local
sort + searchsorted per chunk), then narrows the unsigned-key interval
by that factor on the host — ``ceil(bits / log2(branch))`` passes
(4 for f32, 8 for f64 at the default branch=256) converge every rank to
its EXACT order-statistic key, same total order and bit-exact decode as
the in-memory engine.

Sources: a ``DataStream`` (re-iterated per pass via ``iter_chunks``), a
list/tuple of split-0 ``DNDarray`` chunks, or a zero-arg callable
returning a fresh chunk iterator. Quantile needs a re-iterable source.

Fault site ``data.stream.carry``: an injected (or real) carry-dispatch
failure degrades THAT chunk to the eager accumulation with identical
results, counted in ``data_engine.stream_fallbacks``.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np

from ..core._compat import shard_map
from ..core._sort import _index_dtype
from ..core.dndarray import DNDarray
from ..utils import metrics
from . import engine
from .ops import (AGGS, _ftype, _key_bits, _orderable, _unsigned_dtype,
                  decode_key, unsigned_key)

__all__ = ["stream_groupby", "stream_topk", "stream_quantile"]

_SITE = "data.stream.carry"
_FALLBACK = "data_engine.stream_fallbacks"


def _chunk_iter(source, rows_per_chunk: int):
    if hasattr(source, "iter_chunks"):
        return source.iter_chunks(rows_per_chunk)
    if callable(source):
        return iter(source())
    return iter(source)


def _total_rows(source, rows_per_chunk: int) -> int:
    if hasattr(source, "shape"):
        return int(source.shape[0])
    if isinstance(source, (list, tuple)):
        return sum(int(c.shape[0]) for c in source)
    return sum(int(c.shape[0]) for c in _chunk_iter(source,
                                                    rows_per_chunk))


def _col(chb, col):
    """Extract the value column of a local chunk block (1-D pass-through)."""
    return chb if chb.ndim == 1 else chb[:, col]


def _fold(key, build, carries, chunk_phys, extra, eager, ncarry):
    """One chunk through the donated carry executable (or eager)."""
    args = tuple(carries) + (chunk_phys,) + tuple(extra)
    if engine.enabled():
        out = engine.engine_call(key, build, args, eager, site=_SITE,
                                 fallback_counter=_FALLBACK)
    else:
        out = eager(*args)
    metrics.inc("data_engine.stream_chunks")
    return list(out) if ncarry > 1 else [out]


def _put_carry(arr, comm):
    return jax.device_put(arr, comm.sharding(arr.ndim, 0))


# ---------------------------------------------------------------------- #
# streaming groupby                                                      #
# ---------------------------------------------------------------------- #
def _build_stream_groupby(cshapes, cdts, cphys, cjdt, n_chunk, G, op,
                          key_col, value_col, comm):
    ax = comm.axis_name
    p = comm.size
    c = cphys[0] // p
    idt = _index_dtype()
    ft = _ftype()

    def body(*bufs):
        carries, chb = bufs[:-1], bufs[-1]
        me = jax.lax.axis_index(ax)
        gpos = me.astype(idt) * c + jnp.arange(c, dtype=idt)
        kb = chb[:, key_col].astype(idt)
        valid = (gpos < n_chunk) & (kb >= 0) & (kb < G)
        idx = jnp.where(valid, kb, 0)
        if op == "count":
            part = jnp.zeros((G,), idt).at[idx].add(valid.astype(idt))
            return carries[0] + part[None]
        vb = chb[:, value_col]
        if op == "sum":
            contrib = jnp.where(valid, vb, jnp.zeros((), vb.dtype))
            part = jnp.zeros((G,), vb.dtype).at[idx].add(contrib)
            return carries[0] + part[None]
        if op == "mean":
            vs = jnp.where(valid, vb, jnp.zeros((), vb.dtype)).astype(ft)
            part = jnp.zeros((G,), ft).at[idx].add(vs)
            cnt = jnp.zeros((G,), ft).at[idx].add(valid.astype(ft))
            return carries[0] + part[None], carries[1] + cnt[None]
        vjdt = jnp.dtype(vb.dtype)
        if jnp.issubdtype(vjdt, jnp.floating):
            neutral = jnp.asarray(jnp.inf if op == "min" else -jnp.inf,
                                  vjdt)
        else:
            info = jnp.iinfo(vjdt)
            neutral = jnp.asarray(info.max if op == "min" else info.min,
                                  vjdt)
        contrib = jnp.where(valid, vb, neutral)
        buf = jnp.full((G,), neutral, vjdt)
        part = (buf.at[idx].min(contrib) if op == "min"
                else buf.at[idx].max(contrib))
        comb = (jnp.minimum if op == "min" else jnp.maximum)
        return comb(carries[0], part[None])

    nc = len(cshapes)
    in_specs = tuple(comm.spec(2, 0) for _ in range(nc)) \
        + (comm.spec(2, 0),)
    out_specs = tuple(comm.spec(2, 0) for _ in range(nc))
    return jax.jit(shard_map(
        body, mesh=comm.mesh, in_specs=in_specs,
        out_specs=out_specs if nc > 1 else out_specs[0],
        check_vma=False), donate_argnums=tuple(range(nc)))


def stream_groupby(source, num_groups: int, op: str = "sum",
                   key_col: int = 0, value_col: int = 1,
                   rows_per_chunk: int = 1 << 16) -> DNDarray:
    """Groupby-aggregate over a chunked 2-D stream: ``key_col`` holds
    integral group ids, ``value_col`` the measure. One pass; resident
    memory = one chunk + the ``(p, num_groups)`` carry. Same semantics
    as :func:`heat_tpu.data.groupby_agg`."""
    if op not in AGGS:
        raise ValueError(f"unknown groupby aggregation {op!r}")
    G = int(num_groups)
    if G <= 0:
        raise ValueError("num_groups must be positive")
    metrics.inc("data_engine.groupby_calls")
    idt = _index_dtype()
    ft = _ftype()
    carries = comm = device = None
    cshapes = cdts = None
    for chunk in _chunk_iter(source, rows_per_chunk):
        if chunk.ndim != 2 or chunk.split != 0:
            raise ValueError("stream_groupby needs split-0 2-D chunks")
        if carries is None:
            comm, device = chunk.comm, chunk.device
            p = comm.size
            vjdt = jnp.dtype(chunk.larray.dtype)
            if op == "count":
                cdts = (idt,)
            elif op == "sum":
                cdts = (vjdt,)
            elif op == "mean":
                cdts = (ft, ft)
            else:
                cdts = (vjdt,)
            cshapes = ((p, G),) * len(cdts)
            init = []
            for sh, dt in zip(cshapes, cdts):
                if op in ("min", "max"):
                    if jnp.issubdtype(jnp.dtype(dt), jnp.floating):
                        fill = np.inf if op == "min" else -np.inf
                    else:
                        info = np.iinfo(np.dtype(dt))
                        fill = info.max if op == "min" else info.min
                    init.append(np.full(sh, fill, dt))
                else:
                    init.append(np.zeros(sh, dt))
            carries = [_put_carry(a, comm) for a in init]
        n_chunk = int(chunk.shape[0])
        cphys = tuple(chunk.larray.shape)
        cjdt = jnp.dtype(chunk.larray.dtype)
        key = ("data.stream.groupby", cshapes, tuple(map(str, cdts)),
               cphys, str(cjdt), n_chunk, G, op, key_col, value_col,
               comm.cache_key)

        def eager(*bufs, _n=n_chunk):
            cs, chb = bufs[:-1], bufs[-1]
            ch = chb[:_n]
            kb = ch[:, key_col].astype(idt)
            valid = (kb >= 0) & (kb < G)
            idx = jnp.where(valid, kb, 0)
            if op == "count":
                part = jnp.zeros((G,), idt).at[idx].add(valid.astype(idt))
                return cs[0].at[0].add(part)
            vb = ch[:, value_col]
            if op == "sum":
                contrib = jnp.where(valid, vb, jnp.zeros((), vb.dtype))
                part = jnp.zeros((G,), vb.dtype).at[idx].add(contrib)
                return cs[0].at[0].add(part)
            if op == "mean":
                vs = jnp.where(valid, vb,
                               jnp.zeros((), vb.dtype)).astype(ft)
                part = jnp.zeros((G,), ft).at[idx].add(vs)
                cnt = jnp.zeros((G,), ft).at[idx].add(valid.astype(ft))
                return cs[0].at[0].add(part), cs[1].at[0].add(cnt)
            vjdt2 = jnp.dtype(vb.dtype)
            if jnp.issubdtype(vjdt2, jnp.floating):
                neutral = jnp.asarray(
                    jnp.inf if op == "min" else -jnp.inf, vjdt2)
            else:
                info = jnp.iinfo(vjdt2)
                neutral = jnp.asarray(
                    info.max if op == "min" else info.min, vjdt2)
            contrib = jnp.where(valid, vb, neutral)
            buf = jnp.full((G,), neutral, vjdt2)
            part = (buf.at[idx].min(contrib) if op == "min"
                    else buf.at[idx].max(contrib))
            return (cs[0].at[0].min(part) if op == "min"
                    else cs[0].at[0].max(part))

        carries = _fold(
            key,
            lambda qk, ck, hk, _n=n_chunk, _ph=cphys, _dt=cjdt:
                _build_stream_groupby(cshapes, cdts, _ph, _dt, _n, G,
                                      op, key_col, value_col, comm),
            carries, chunk.larray, (), eager, len(cdts))
    if carries is None:
        raise ValueError("stream_groupby: empty stream")
    host = [np.asarray(a) for a in carries]
    if op in ("sum", "count"):
        res = host[0].sum(axis=0)
    elif op == "mean":
        with np.errstate(invalid="ignore", divide="ignore"):
            res = host[0].sum(axis=0) / host[1].sum(axis=0)
    elif op == "min":
        res = host[0].min(axis=0)
    else:
        res = host[0].max(axis=0)
    return DNDarray.from_logical(jnp.asarray(res), None, device, comm)


# ---------------------------------------------------------------------- #
# streaming top-k                                                        #
# ---------------------------------------------------------------------- #
def _build_stream_topk(cphys, cjdt, n_chunk, k, largest, col, comm,
                       ukdt, invalid_pos):
    ax = comm.axis_name
    p = comm.size
    c = cphys[0] // p
    idt = _index_dtype()

    def body(cs, cp, chb, off):
        me = jax.lax.axis_index(ax)
        gpos = me.astype(idt) * c + jnp.arange(c, dtype=idt)
        valid = gpos < n_chunk
        vb = _col(chb, col)
        uk = unsigned_key(vb)
        sel = jnp.where(valid, uk if largest else ~uk,
                        jnp.zeros((), ukdt))
        sv, si = jax.lax.top_k(sel, k)
        npos = jnp.where(valid[si], off + gpos[si], invalid_pos)
        cat_s = jnp.concatenate([cs[0], sv])
        cat_p = jnp.concatenate([cp[0], npos])
        order = jnp.lexsort((cat_p, ~cat_s))[:k]
        return cat_s[order][None], cat_p[order][None]

    nd = len(cphys)
    return jax.jit(shard_map(
        body, mesh=comm.mesh,
        in_specs=(comm.spec(2, 0), comm.spec(2, 0), comm.spec(nd, 0),
                  comm.spec(0, None)),
        out_specs=(comm.spec(2, 0), comm.spec(2, 0)),
        check_vma=False), donate_argnums=(0, 1))


def stream_topk(source, k: int, largest: bool = True, col=None,
                rows_per_chunk: int = 1 << 16):
    """Top-k over a chunked stream (1-D chunks, or 2-D with ``col``).
    Positions index the logical stream rows. Resident memory = one chunk
    + the ``(p, k)`` candidate carry. Same total order as
    :func:`heat_tpu.data.topk`."""
    k = int(k)
    if k < 1:
        raise ValueError("k must be positive")
    metrics.inc("data_engine.topk_calls")
    idt = _index_dtype()
    invalid_pos = np.iinfo(np.dtype(idt)).max
    carries = comm = device = jdt = ukdt = None
    offset = 0
    for chunk in _chunk_iter(source, rows_per_chunk):
        if chunk.split != 0:
            raise ValueError("stream_topk needs split-0 chunks")
        vjdt = jnp.dtype(chunk.larray.dtype)
        if not _orderable(vjdt):
            raise TypeError(f"stream_topk: unordered dtype {vjdt}")
        if carries is None:
            comm, device, jdt = chunk.comm, chunk.device, vjdt
            p = comm.size
            ukdt = _unsigned_dtype(_key_bits(jdt))
            carries = [
                _put_carry(np.zeros((p, k), ukdt), comm),
                _put_carry(np.full((p, k), invalid_pos, idt), comm)]
        n_chunk = int(chunk.shape[0])
        cphys = tuple(chunk.larray.shape)
        c = cphys[0] // comm.size
        off = np.asarray(offset, idt)

        def eager(cs, cp, chb, o, _n=n_chunk):
            vb = _col(chb[:_n], col)
            uk = unsigned_key(vb)
            sel = uk if largest else ~uk
            pos = o + jnp.arange(_n, dtype=idt)
            cat_s = jnp.concatenate([cs[0], sel])
            cat_p = jnp.concatenate([cp[0], pos])
            order = jnp.lexsort((cat_p, ~cat_s))[:k]
            return (cs.at[0].set(cat_s[order]),
                    cp.at[0].set(cat_p[order]))

        if k <= c:
            key = ("data.stream.topk", cphys, str(vjdt), n_chunk, k,
                   bool(largest), col, comm.cache_key)
            carries = _fold(
                key,
                lambda qk, ck, hk, _n=n_chunk, _ph=cphys:
                    _build_stream_topk(_ph, vjdt, _n, k, largest, col,
                                       comm, ukdt, invalid_pos),
                carries, chunk.larray, (off,), eager, 2)
        else:  # chunk smaller than k: merge it eagerly
            carries = list(eager(carries[0], carries[1],
                                 chunk.larray, off))
            metrics.inc("data_engine.stream_chunks")
        offset += n_chunk
    if carries is None:
        raise ValueError("stream_topk: empty stream")
    if k > offset:
        raise ValueError(f"k={k} out of range for {offset} rows")
    sel = np.asarray(carries[0]).reshape(-1)
    pos = np.asarray(carries[1]).reshape(-1)
    order = np.lexsort((pos, np.invert(sel)))[:k]
    sel_t, pos_t = sel[order], pos[order]
    uk_t = sel_t if largest else np.invert(sel_t)
    vals = decode_key(jnp.asarray(uk_t, ukdt), jdt)
    return (DNDarray.from_logical(vals, None, device, comm),
            DNDarray.from_logical(jnp.asarray(pos_t, idt), None, device,
                                  comm))


# ---------------------------------------------------------------------- #
# streaming quantile                                                     #
# ---------------------------------------------------------------------- #
def _build_stream_quantile(cphys, cjdt, n_chunk, nbins, col, comm, ukdt,
                           umax):
    ax = comm.axis_name
    p = comm.size
    c = cphys[0] // p
    idt = _index_dtype()
    floating = jnp.issubdtype(jnp.dtype(cjdt), jnp.floating)

    def body(carry, ncarry, chb, pivots):
        me = jax.lax.axis_index(ax)
        gpos = me.astype(idt) * c + jnp.arange(c, dtype=idt)
        valid = gpos < n_chunk
        vb = _col(chb, col)
        uk = unsigned_key(vb)
        su = jnp.sort(jnp.where(valid, uk, umax))
        cnt = jnp.searchsorted(su, pivots, side="right").astype(jnp.int64)
        nn = (jnp.sum(valid & jnp.isnan(vb)).astype(jnp.int64)
              if floating else jnp.zeros((), jnp.int64))
        return carry + cnt[None], ncarry + nn[None]

    nd = len(cphys)
    return jax.jit(shard_map(
        body, mesh=comm.mesh,
        in_specs=(comm.spec(2, 0), comm.spec(1, 0), comm.spec(nd, 0),
                  comm.spec(1, None)),
        out_specs=(comm.spec(2, 0), comm.spec(1, 0)),
        check_vma=False), donate_argnums=(0, 1))


def stream_quantile(source, q, col=None, rows_per_chunk: int = 1 << 16,
                    interpolation: str = "linear", branch: int = 256):
    """EXACT quantiles (``q`` in [0, 1], scalar or sequence) of a
    chunked stream via multi-pass ``branch``-way bisection on the
    unsigned key line — ``ceil(bits/log2(branch))`` passes over the
    (re-iterable) source, resident memory bounded by one chunk + the
    count carry. NaN anywhere poisons the result (numpy parity).
    Returns a python float / numpy array (host values)."""
    q_np = np.asarray(q, dtype=np.float64)
    if q_np.size and not bool((q_np >= 0).all() and (q_np <= 1).all()):
        raise ValueError("Quantiles must be in the range [0, 1]")
    if interpolation not in ("linear", "lower", "higher", "nearest",
                             "midpoint"):
        raise ValueError(f"unknown interpolation method {interpolation!r}")
    branch = max(int(branch), 2)
    metrics.inc("data_engine.quantile_calls")
    n = _total_rows(source, rows_per_chunk)
    if n <= 0:
        raise ValueError("stream_quantile: empty stream")
    # one probe chunk for dtype/mesh metadata (re-iterable source)
    first = next(_chunk_iter(source, rows_per_chunk))
    comm, device = first.comm, first.device
    jdt = jnp.dtype(first.larray.dtype)
    if not _orderable(jdt):
        raise TypeError(f"stream_quantile: unordered dtype {jdt}")
    floating = jnp.issubdtype(jdt, jnp.floating)
    del first
    p = comm.size
    bits = _key_bits(jdt)
    ukdt = _unsigned_dtype(bits)
    umax_py = (1 << bits) - 1
    umax = np.asarray(umax_py, ukdt)
    # target ranks (0-based) per quantile
    targets = []
    for qv in q_np.reshape(-1):
        f = (n - 1) * float(qv)
        lo_r, hi_r = int(np.floor(f)), int(np.ceil(f))
        if interpolation == "lower":
            need = (lo_r,)
        elif interpolation == "higher":
            need = (hi_r,)
        elif interpolation == "nearest":
            need = (int(np.round(f)),)
        else:
            need = (lo_r, hi_r)
        targets.append((f, lo_r, hi_r, need))
    ranks = sorted({r for _, _, _, need in targets for r in need})
    m = len(ranks)
    lo = [0] * m
    hi = [umax_py] * m
    nan_total = None
    passes = 0
    while any(lo[i] < hi[i] for i in range(m)) or nan_total is None:
        grids = []
        for i in range(m):
            width = hi[i] - lo[i] + 1
            grid = sorted({max(lo[i] + (j * width) // branch - 1, lo[i])
                           for j in range(1, branch + 1)} | {hi[i]})
            grid = (grid + [hi[i]] * branch)[:branch]
            grids.append(grid)
        # element-wise np.uint64(): list->array conversion routes through
        # C long and overflows for values in [2^63, 2^64)
        pivots_np = np.array([[np.uint64(v) for v in g] for g in grids],
                             dtype=np.uint64).astype(ukdt)
        pivots_flat = jnp.asarray(pivots_np.reshape(-1))
        carry = _put_carry(np.zeros((p, m * branch), np.int64), comm)
        ncarry = _put_carry(np.zeros((p,), np.int64), comm)
        carries = [carry, ncarry]
        for chunk in _chunk_iter(source, rows_per_chunk):
            if chunk.split != 0:
                raise ValueError("stream_quantile needs split-0 chunks")
            n_chunk = int(chunk.shape[0])
            cphys = tuple(chunk.larray.shape)
            key = ("data.stream.quantile", cphys, str(jdt), n_chunk,
                   m * branch, col, comm.cache_key)

            def eager(ca, nc, chb, pv, _n=n_chunk):
                vb = _col(chb[:_n], col)
                uk = unsigned_key(vb)
                cnt = jnp.sum(uk[None, :] <= pv[:, None],
                              axis=1).astype(jnp.int64)
                nn = (jnp.sum(jnp.isnan(vb)).astype(jnp.int64)
                      if floating else jnp.zeros((), jnp.int64))
                return ca.at[0].add(cnt), nc.at[0].add(nn)

            carries = _fold(
                key,
                lambda qk, ck, hk, _n=n_chunk, _ph=cphys:
                    _build_stream_quantile(_ph, jdt, _n, branch, col,
                                           comm, ukdt, umax),
                carries, chunk.larray, (pivots_flat,), eager, 2)
        counts = np.asarray(carries[0]).sum(axis=0).reshape(m, branch)
        if nan_total is None:
            nan_total = int(np.asarray(carries[1]).sum())
        for i in range(m):
            if lo[i] >= hi[i]:
                continue
            row, grid = counts[i], grids[i]
            j = int(np.argmax(row >= ranks[i] + 1))
            hi[i] = grid[j]
            lo[i] = (grid[j - 1] + 1) if j > 0 else lo[i]
        passes += 1
        if passes > bits:  # defensive: can't exceed one pass per bit
            raise RuntimeError("stream_quantile failed to converge")
    vals = np.asarray(decode_key(
        jnp.asarray(np.array([np.uint64(v) for v in lo],
                             dtype=np.uint64).astype(ukdt)), jdt))
    by_rank = {r: vals[i] for i, r in enumerate(ranks)}
    ft = np.float64 if jax.config.jax_enable_x64 else np.float32
    out = []
    for f, lo_r, hi_r, _ in targets:
        if interpolation == "lower":
            r = ft(by_rank[lo_r])
        elif interpolation == "higher":
            r = ft(by_rank[hi_r])
        elif interpolation == "nearest":
            r = ft(by_rank[int(np.round(f))])
        elif interpolation == "midpoint":
            r = (ft(by_rank[lo_r]) + ft(by_rank[hi_r])) / 2
        else:
            a = ft(by_rank[lo_r])
            r = a if hi_r == lo_r else \
                a + (ft(by_rank[hi_r]) - a) * ft(f - lo_r)
        if floating and nan_total:
            r = ft(np.nan)
        out.append(r)
    if q_np.ndim == 0:
        return float(out[0]) if not np.isnan(out[0]) else float("nan")
    return np.asarray(out, ft).reshape(q_np.shape)
