"""Functional NN ops (reference ``heat/nn/functional.py:9-33`` passes through
``torch.nn.functional``; here the passthrough target is ``jax.nn``)."""

from __future__ import annotations

import jax.nn as _jnn
import jax.numpy as _jnp

relu = _jnn.relu
sigmoid = _jnn.sigmoid
softmax = _jnn.softmax
log_softmax = _jnn.log_softmax
gelu = _jnn.gelu
silu = _jnn.silu
swish = _jnn.silu
elu = _jnn.elu
leaky_relu = _jnn.leaky_relu
tanh = _jnp.tanh
one_hot = _jnn.one_hot


def cross_entropy(logits, labels, axis: int = -1):
    """Mean cross-entropy of integer labels against logits."""
    logp = _jnn.log_softmax(logits, axis=axis)
    picked = _jnp.take_along_axis(logp, labels[..., None], axis=axis)[..., 0]
    return -_jnp.mean(picked)


def mse_loss(pred, target):
    return _jnp.mean((pred - target) ** 2)


def nll_loss(logp, labels, axis: int = -1):
    picked = _jnp.take_along_axis(logp, labels[..., None], axis=axis)[..., 0]
    return -_jnp.mean(picked)


def __getattr__(name):
    try:
        return getattr(_jnn, name)
    except AttributeError:
        raise AttributeError(f"module 'heat_tpu.nn.functional' has no attribute {name!r}")
