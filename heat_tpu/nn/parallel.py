"""Tensor, pipeline and expert parallelism over the mesh.

The reference's parallelism inventory (SURVEY.md §2.6) covers data
parallelism (arrays + training), implicit tensor parallelism for linalg,
and the sequence-parallel *primitives* (halo, ring, all-to-all); pipeline
and expert parallelism are absent, and tensor parallelism never reaches the
NN layer. This module completes the grid: Megatron-style tensor-parallel
layers, a GPipe-style pipeline over a named mesh axis, and Switch/GShard
top-1 expert parallelism — all as per-device functions composable inside
one ``shard_map`` program, so dp x pp x tp x sp x ep combine in a single
compiled train step (see :mod:`heat_tpu.nn.transformer`).

Design notes (TPU-first):

* Tensor parallel: the column/row-parallel pairing keeps ONE ``psum`` per
  MLP / attention block (Megatron's schedule); XLA overlaps it with the
  adjacent GEMMs over ICI.
* Pipeline: stage weights live in a leading stage axis sharded over the
  ``pp`` mesh axis; activations flow stage-to-stage via ``ppermute`` inside
  a ``lax.scan`` over ``n_micro + pp - 1`` ticks (GPipe schedule). The scan
  is differentiable — the transpose of ``ppermute`` is the reverse
  ``ppermute`` — so one ``jax.grad`` drives the whole 1F1B-equivalent
  backward.
* Expert parallel: GShard dispatch/combine einsums with a static capacity
  (TPU static shapes); token routing between devices is one ``all_to_all``
  each way (the reference's Alltoallw resplit primitive,
  ``communication.py:1199-1341``, re-purposed for MoE routing).
"""

from __future__ import annotations

import math
from functools import partial
from typing import Callable, Optional

import jax
import jax.numpy as jnp
from jax import lax

__all__ = [
    "column_parallel_dense",
    "row_parallel_dense",
    "tp_mlp",
    "tp_attention_qkv",
    "tp_attention_out",
    "switch_moe",
    "moe_capacity",
    "pipeline_apply",
]


# --------------------------------------------------------------------- #
# Megatron-style tensor parallelism (per-device code, inside shard_map) #
# --------------------------------------------------------------------- #

def column_parallel_dense(x, w_shard, b_shard=None, *, axis: Optional[str] = None,
                          gather_output: bool = False):
    """``y = x @ W`` with ``W`` column-sharded over the ``tp`` axis.

    Input ``x`` is replicated over tp; output is feature-sharded — zero
    communication (unless ``gather_output``). Pair with
    :func:`row_parallel_dense` so the whole block costs one ``psum``.
    """
    y = x @ w_shard
    if b_shard is not None:
        y = y + b_shard
    if gather_output:
        if axis is None:
            raise ValueError("gather_output=True needs the tp axis name")
        y = lax.all_gather(y, axis, axis=y.ndim - 1, tiled=True)
    return y


def row_parallel_dense(x_shard, w_shard, b=None, *, axis: str):
    """``y = psum_tp(x_shard @ W_shard)`` with ``W`` row-sharded over tp.

    Input is feature-sharded (a column-parallel output); the partial
    products are summed over the tp axis — the single collective of the
    Megatron block. The (replicated) bias is added after the psum.
    """
    y = lax.psum(x_shard @ w_shard, axis)
    if b is not None:
        y = y + b
    return y


def tp_mlp(x, w_up_shard, w_down_shard, *, axis: str,
           activation: Callable = jax.nn.gelu, b_up_shard=None, b_down=None):
    """Tensor-parallel transformer MLP: column-parallel up-projection,
    activation on the shard, row-parallel down-projection (one psum)."""
    h = column_parallel_dense(x, w_up_shard, b_up_shard)
    return row_parallel_dense(activation(h), w_down_shard, b_down, axis=axis)


def tp_attention_qkv(x, wqkv_shard, n_heads_shard: int):
    """QKV projection with heads sharded over tp.

    ``wqkv_shard``: ``(D, 3 * H_shard * Dh)`` — the columns for this
    device's head subset. Returns ``(q, k, v)`` each
    ``(..., S, H_shard, Dh)``.
    """
    h = x @ wqkv_shard
    q, k, v = jnp.split(h, 3, axis=-1)
    Dh = q.shape[-1] // n_heads_shard

    def heads(t):
        return t.reshape(*t.shape[:-1], n_heads_shard, Dh)

    return heads(q), heads(k), heads(v)


def tp_attention_out(attn_shard, wproj_shard, *, axis: str):
    """Output projection of tp-sharded attention: flatten the local head
    subset, row-parallel project, psum over tp (the block's one collective)."""
    flat = attn_shard.reshape(*attn_shard.shape[:-2], -1)
    return row_parallel_dense(flat, wproj_shard, axis=axis)


# --------------------------------------------------------------------- #
# Switch / GShard top-1 expert parallelism                              #
# --------------------------------------------------------------------- #

def moe_capacity(tokens_local: int, n_experts: int, capacity_factor: float) -> int:
    """Static per-(source device, expert) buffer size."""
    return max(1, int(math.ceil(tokens_local * capacity_factor / n_experts)))


def switch_moe(x, router_w, expert_up_shard, expert_down_shard, *, axis: str,
               capacity_factor: float = 1.25,
               activation: Callable = jax.nn.gelu):
    """Top-1 (Switch) mixture-of-experts with experts sharded over ``axis``.

    Per-device code for ``shard_map``. Shapes (per device):

    * ``x``: ``(T, D)`` local tokens (flatten batch x seq first)
    * ``router_w``: ``(D, E)`` replicated, ``E = ep * E_local``
    * ``expert_up_shard``: ``(E_local, D, F)``; ``expert_down_shard``:
      ``(E_local, F, D)`` — this device's experts.

    Routing: GShard dispatch/combine einsums with static capacity
    ``C = ceil(T * capacity_factor / E)`` per (source device, expert);
    overflow tokens fall through the residual (standard Switch drop
    semantics). Cross-device movement is one ``all_to_all`` each way.
    """
    T, D = x.shape
    E_local, _, F = expert_up_shard.shape
    ep = lax.psum(1, axis)  # axis size, available inside shard_map
    E = ep * E_local
    C = moe_capacity(T, E, capacity_factor)

    # --- router (local) --- #
    logits = x @ router_w                        # (T, E)
    probs = jax.nn.softmax(logits, axis=-1)
    expert_idx = jnp.argmax(probs, axis=-1)      # (T,)
    gate = jnp.take_along_axis(probs, expert_idx[:, None], axis=-1)[:, 0]

    onehot = jax.nn.one_hot(expert_idx, E, dtype=x.dtype)          # (T, E)
    pos = jnp.cumsum(onehot, axis=0) * onehot - 1.0                # slot per token
    kept = (pos >= 0) & (pos < C)
    pos_oh = jax.nn.one_hot(pos.astype(jnp.int32), C, dtype=x.dtype)
    dispatch = pos_oh * kept[..., None].astype(x.dtype)            # (T, E, C)
    combine = dispatch * gate[:, None, None]                       # (T, E, C)

    # --- dispatch to expert shards: one all_to_all --- #
    expert_in = jnp.einsum("tec,td->ecd", dispatch, x)             # (E, C, D)
    expert_in = expert_in.reshape(ep, E_local, C, D)
    # send block i to device i; received blocks stack on the (new) source axis
    expert_in = lax.all_to_all(expert_in, axis, split_axis=0, concat_axis=0)
    # (ep_src, E_local, C, D): this device's experts, tokens from every source

    # --- expert FFN on the local expert subset --- #
    h = activation(jnp.einsum("secd,edf->secf", expert_in, expert_up_shard))
    expert_out = jnp.einsum("secf,efd->secd", h, expert_down_shard)

    # --- return to sources: the inverse all_to_all --- #
    expert_out = lax.all_to_all(expert_out, axis, split_axis=0, concat_axis=0)
    expert_out = expert_out.reshape(E, C, D)

    # --- combine (local) --- #
    return jnp.einsum("tec,ecd->td", combine, expert_out)


# --------------------------------------------------------------------- #
# GPipe pipeline parallelism                                            #
# --------------------------------------------------------------------- #

def vma_capable() -> bool:
    """Whether this jax can express varying-across-mesh-axes (vma/rep)
    typing — the single capability gate for keeping identity psums whose
    only job is clearing an axis-varying type (``pipeline_apply``'s
    pp==1 branch, ``TransformerLM._psum_tp``). Superset probe: any of
    the vma-era APIs present means the typing system may be live."""
    import jax as _jax

    return (hasattr(_jax, "typeof") or hasattr(lax, "pcast")
            or hasattr(lax, "pvary"))


def pipeline_apply(stage_fn: Callable, stage_params, x_micro, *, axis: str):
    """Run ``pp`` pipeline stages over microbatches (per-device, shard_map).

    * ``stage_fn(params, x) -> y``: one stage's computation; activations
      must keep a fixed shape across stages.
    * ``stage_params``: this device's stage parameters (the global pytree
      carries a leading stage axis sharded over ``axis``; inside shard_map
      each device sees leading dim 1 — pass it squeezed or indexed).
    * ``x_micro``: ``(n_micro, mb, ...)`` microbatched input, replicated
      over the pp axis.

    GPipe schedule: ``T = n_micro + pp - 1`` ticks in a ``lax.scan``; at
    each tick every device computes its stage on the activation received
    via ``ppermute`` from the previous stage (stage 0 feeds the next
    microbatch) and passes the result on. Outputs are collected on the
    last stage and broadcast with a masked ``psum``. Differentiable end to
    end (scan + ppermute transpose), so ``jax.grad`` of a loss on the
    output drives the full pipeline backward pass.

    Gradient pattern: because the output is replicated over ``pp`` via a
    ``psum`` broadcast, a training loss must be counted ONCE globally —
    mask it to the last stage and ``psum``::

        out = pipeline_apply(stage_fn, params, x_micro, axis="pp")
        l = lax.psum(loss(out) * (lax.axis_index("pp") == pp - 1), "pp")

    so the cotangent enters the collective's transpose on exactly one
    device and per-stage parameter gradients land on the owning device
    with no replication factor.
    """
    pp = lax.psum(1, axis)
    stage = lax.axis_index(axis)
    n_micro = x_micro.shape[0]
    T = n_micro + pp - 1
    perm = [(i, i + 1) for i in range(pp - 1)]  # no wraparound

    # the rotating buffers assume the stage preserves dtype (a dtype change
    # would silently corrupt the masked writes). Checked on EVERY path —
    # incl. the degenerate pp==1 mesh developers test on — so the contract
    # fails loud before a real pipeline deployment
    out_struct = jax.eval_shape(stage_fn, stage_params, x_micro[0])
    if out_struct.dtype != x_micro.dtype:
        raise TypeError(
            f"pipeline stage changed activation dtype {x_micro.dtype} -> "
            f"{out_struct.dtype}; keep compute dtype uniform across stages "
            "(cast params inside the stage, not activations between stages)")

    if pp == 1:
        # degenerate pipeline: run the stage per microbatch (scan, not vmap —
        # the stage may contain collectives over other axes). The identity
        # psum clears the axis-varying type the (pp-sharded) stage params
        # impart under vma tracking, matching the pp>1 branch's out type;
        # without vma tracking it is a pure identity that still lowers to
        # a singleton-group all-reduce PAIR through forward+backward —
        # skip it there so the packed train step's collective audit stays
        # exactly the plan's count (same capability gate as below)
        _, out = lax.scan(
            lambda c, xm: (c, stage_fn(stage_params, xm)), 0, x_micro)
        if vma_capable():
            out = lax.psum(out, axis)
        return out

    # initial carries are device-varying (they hold per-stage activations);
    # on jax versions without vma tracking (no pcast/pvary) the annotation
    # is unnecessary and the identity is correct
    if hasattr(lax, "pcast"):
        _vary = partial(lax.pcast, to="varying")
    elif hasattr(lax, "pvary"):
        _vary = lax.pvary
    else:
        _vary = lambda x, _axis: x  # noqa: E731
    out_buf = _vary(jnp.zeros_like(x_micro), axis)
    recv = _vary(jnp.zeros_like(x_micro[0]), axis)

    def tick(carry, t):
        recv, out_buf = carry
        # stage 0 reads microbatch t (zeros once the feed is exhausted)
        feed = lax.dynamic_index_in_dim(
            x_micro, jnp.minimum(t, n_micro - 1), keepdims=False)
        feed = jnp.where(t < n_micro, feed, jnp.zeros_like(feed))
        x_in = jnp.where(stage == 0, feed, recv)
        y = stage_fn(stage_params, x_in)
        # last stage stores microbatch t-(pp-1) when in range; the masked
        # write (no lax.cond) keeps branch types uniform under vma tracking
        slot = t - (pp - 1)
        store = (stage == pp - 1) & (slot >= 0)
        slot_c = jnp.clip(slot, 0, n_micro - 1)
        cur = lax.dynamic_index_in_dim(out_buf, slot_c, keepdims=False)
        out_buf = lax.dynamic_update_index_in_dim(
            out_buf, jnp.where(store, y, cur), slot_c, axis=0)
        recv = lax.ppermute(y, axis, perm)
        return (recv, out_buf), None

    (recv, out_buf), _ = lax.scan(tick, (recv, out_buf), jnp.arange(T))
    # broadcast the last stage's buffer to every pp rank
    mask = (stage == pp - 1).astype(out_buf.dtype)
    return lax.psum(out_buf * mask, axis)
