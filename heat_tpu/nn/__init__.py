"""Neural network layer (reference ``heat/nn/``).

The reference exposes ``torch.nn`` lazily via module ``__getattr__``
(``heat/nn/__init__.py:19-48``) and adds only ``DataParallel`` /
``DataParallelMultiGPU``. The TPU-native equivalent passes through
``flax.linen`` the same way (``ht.nn.Dense``, ``ht.nn.Conv`` …, plus
torch-style aliases) and adds :class:`DataParallel` — data-parallel training
over the mesh with GSPMD gradient psum instead of per-parameter MPI hooks.
"""

from __future__ import annotations

import flax.linen as _linen

from .data_parallel import DataParallel, DataParallelMultiGPU
from . import functional
from . import functional as F
from . import attention
from .attention import local_attention, ring_attention, ulysses_attention
from . import parallel
from . import transformer
from .transformer import TransformerLM, TransformerLMConfig
from .parallel import (
    column_parallel_dense,
    row_parallel_dense,
    tp_mlp,
    switch_moe,
    pipeline_apply,
)

__all__ = [
    "DataParallel",
    "DataParallelMultiGPU",
    "functional",
    "F",
    "attention",
    "local_attention",
    "ring_attention",
    "ulysses_attention",
    "parallel",
    "transformer",
    "TransformerLM",
    "TransformerLMConfig",
    "column_parallel_dense",
    "row_parallel_dense",
    "tp_mlp",
    "switch_moe",
    "pipeline_apply",
]

# torch-style aliases onto flax.linen (parity with the reference's
# torch.nn passthrough, ``heat/nn/__init__.py:19-48``)
_ALIASES = {
    "Linear": "Dense",
    "Conv1d": "Conv",
    "Conv2d": "Conv",
    "BatchNorm1d": "BatchNorm",
    "BatchNorm2d": "BatchNorm",
    "Embedding": "Embed",
}


def __getattr__(name):
    if name in _ALIASES:
        return getattr(_linen, _ALIASES[name])
    try:
        return getattr(_linen, name)
    except AttributeError:
        raise AttributeError(f"module 'heat_tpu.nn' has no attribute {name!r}")
