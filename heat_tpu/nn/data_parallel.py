"""Data-parallel training over the mesh (reference ``heat/nn/data_parallel.py``).

The reference wraps a torch module with per-parameter backward hooks that
``(I)Allreduce`` gradients over MPI (``data_parallel.py:223-297``), with
identical-seed initialization on every rank (``:108``). The TPU-native
re-design keeps the *semantics* — replicated parameters, batch sharded over
the mesh, gradients averaged across shards every step — but realizes them as
one fused jitted train step: with the batch sharded ``P('proc')`` and the
parameters replicated, XLA inserts the gradient ``psum`` over ICI
automatically and overlaps it with the backward pass (the reference's
non-blocking ``Iallreduce``+wait-handle machinery ``:175-221`` is exactly
what the XLA scheduler does for free).
"""

from __future__ import annotations

from typing import Any, Callable, Optional

import numpy as np

import jax
import jax.numpy as jnp

from ..core.communication import sanitize_comm
from ..core.dndarray import DNDarray

__all__ = ["DataParallel", "DataParallelMultiGPU"]


def _as_jax(x):
    if isinstance(x, DNDarray):
        return x.larray
    return jnp.asarray(x)


class DataParallel:
    """Data-parallel wrapper around a flax module (reference ``data_parallel.py:21``).

    Parameters
    ----------
    module : flax.linen.Module
        The network. Parameters are initialized once (single seed — the
        replicated analogue of the reference's unified-seed init) and kept
        replicated on the mesh.
    comm : TPUCommunication, optional
    optimizer : heat_tpu.optim.DataParallelOptimizer, optional
        Wraps an optax optimizer; required for :meth:`step`.
    loss_fn : callable(params, apply_fn, batch_x, batch_y) -> scalar, or
        callable(logits, y) -> scalar (detected by arity), default
        cross-entropy on integer labels.
    blocking_parameter_updates : bool
        API parity with the reference (``:52``); the XLA schedule always
        overlaps communication with compute, so both modes are the fused
        step.
    loss_is_batch_mean : bool, optional
        Declares that ``loss_fn`` is a per-example MEAN over the batch
        (plus optional replicated additive terms) — the decomposition the
        packed-collective train step relies on (global mean == mean of
        equal-shard means). Defaults to True for the built-in
        cross-entropy and False for user losses: a sum-reduction loss
        under the packed step would silently scale gradients by 1/world,
        so custom losses keep the exact GSPMD step unless the caller
        opts in here.
    """

    def __init__(
        self,
        module,
        comm=None,
        optimizer=None,
        loss_fn: Optional[Callable] = None,
        blocking_parameter_updates: bool = False,
        seed: int = 0,
        loss_is_batch_mean: Optional[bool] = None,
    ):
        self.module = module
        self.comm = sanitize_comm(comm)
        self.optimizer = optimizer
        self.blocking_parameter_updates = blocking_parameter_updates
        self.seed = seed
        self.params = None
        self._train_step = None
        # (fusion.quant_key(), fusion.chunk_key(), fusion.hier_key()) ->
        # (packed step, its trace-time qinfo dict): codec/chunk/tier
        # toggles compile SIBLINGS and toggle-back re-hits the cached
        # exact/unchunked/flat program (same discipline as
        # TransformerLM's _step_cache; the key space is the handful of
        # codec × chunk × tier configs)
        self._packed_steps = {}
        if loss_is_batch_mean is None:
            loss_is_batch_mean = loss_fn is None  # default CE is a mean
        self.loss_is_batch_mean = bool(loss_is_batch_mean)
        if loss_fn is None:
            from . import functional

            loss_fn = lambda logits, y: functional.cross_entropy(logits, y)
        self.loss_fn = loss_fn
        if optimizer is not None:
            optimizer._attach(self)

    # ------------------------------------------------------------------ #
    def init(self, sample_input) -> None:
        """Initialize replicated parameters (reference seed-unified init ``:108``)."""
        sample = _as_jax(sample_input)
        key = jax.random.key(self.seed)
        self.params = self.module.init(key, sample)
        if self.optimizer is not None:
            self.optimizer.reset_state(self.params)

    def __call__(self, x):
        """Forward pass (reference forward with hook finalization ``:140-172``)."""
        if self.params is None:
            self.init(x)
        xa = _as_jax(x)
        out = self.module.apply(self.params, xa)
        if isinstance(x, DNDarray):
            return DNDarray.from_logical(out, x.split, x.device, x.comm)
        return out

    forward = __call__

    # ------------------------------------------------------------------ #
    def _build_train_step(self):
        apply_fn = self.module.apply
        loss_fn = self.loss_fn
        tx = self.optimizer.tx

        def train_step(params, opt_state, bx, by):
            def loss(p):
                logits = apply_fn(p, bx)
                return loss_fn(logits, by)

            lval, grads = jax.value_and_grad(loss)(params)
            updates, opt_state = tx.update(grads, opt_state, params)
            import optax

            params = optax.apply_updates(params, updates)
            return params, opt_state, lval

        return jax.jit(train_step, donate_argnums=(0, 1))

    def _tier_factor(self, hier=None):
        """The declared ``(dcn, ici)`` factorization of this trainer's
        flat mesh, or None: the ``HEAT_TPU_MESH_TIERS`` integer form when
        it exactly factors the device count, else — on a real multi-host
        pod with no explicit declaration — the process boundary itself
        (``jax.process_count()`` hosts × devices-per-host). Gated on the
        hierarchy master switch. ``hier`` pins the :func:`hier_key` the
        caller cache-keyed on (captured-key discipline: a concurrent
        declaration change between keying and building must not produce
        a program whose grid contradicts its key); None keeps the
        historic flat 1-D grid."""
        from ..core import fusion

        hk = hier if hier is not None else fusion.hier_key()
        if not hk[0]:
            return None
        n = self.comm.size
        f = fusion._hier_factor(n, hk)
        if f is not None:
            return f
        pc = jax.process_count()
        if hk[1] is None and 1 < pc < n and n % pc == 0:
            return (pc, n // pc)
        return None

    def _build_packed_train_step(self, quant=None, chunks=None, hier=None):
        """The packed-collective form of the train step: one ``shard_map``
        program computing each device's gradients on its LOCAL batch shard
        and combining every parameter cotangent — and the loss — in ONE
        flattened all-reduce (:func:`heat_tpu.core.fusion.packed_psum`,
        the arXiv:2004.09362 generalized-allreduce packing; the
        reference's per-parameter Allreduce hooks collapse into it),
        instead of the one-all-reduce-per-parameter GSPMD places for the
        transposed batch sharding. Exact for batch-mean losses (equal
        canonical shards): the global mean is the mean of per-shard means,
        plus any replicated additive terms (regularizers).

        With tiers declared (:meth:`_tier_factor`) the flat dp grid
        defaults to 2-D — ``MeshGrid((d, i), ("dcn", "ici"))`` over the
        SAME devices in the same order, so per-device batch shards are
        identical to the flat layout — and the packed all-reduce
        decomposes hierarchically: reduce-scatter inside each ICI group,
        all-reduce of the 1/i shard across DCN (with the DCN wire
        codec), all-gather back (``HEAT_TPU_HIER``)."""
        import optax

        from ..core import fusion
        from ..core._compat import shard_map
        from ..core.communication import MeshGrid
        from jax.sharding import PartitionSpec as P

        apply_fn = self.module.apply
        loss_fn = self.loss_fn
        tx = self.optimizer.tx
        comm = self.comm
        p = comm.size
        qinfo = {}
        if quant is None:
            quant = fusion.quant_key()
        if chunks is None:
            chunks = fusion.chunk_key()
        if hier is None:
            hier = fusion.hier_key()
        f = self._tier_factor(hier)
        if f is not None:
            grid = MeshGrid(f, ("dcn", "ici"), devices=comm.devices)
            mesh, axes = grid.mesh, ("dcn", "ici")
            batch_spec = P(("dcn", "ici"))
        else:
            mesh, axes = comm.mesh, (comm.axis_name,)
            batch_spec = P(comm.axis_name)

        def body(params, opt_state, bx, by):
            # reset-then-accumulate runs once per trace; step() reads the
            # stable dict per dispatch for the op_engine.quant_* counters
            fusion.reset_qinfo(qinfo)

            def local_loss(prm):
                return loss_fn(apply_fn(prm, bx), by)

            lval, grads = jax.value_and_grad(local_loss)(params)
            leaves, treedef = jax.tree_util.tree_flatten(grads)
            packed = fusion.packed_psum(leaves + [lval], axes,
                                        qinfo=qinfo, quant=quant,
                                        chunks=chunks, hier=hier)
            grads = jax.tree_util.tree_unflatten(
                treedef, [g / p for g in packed[:-1]])
            updates, opt_state = tx.update(grads, opt_state, params)
            params = optax.apply_updates(params, updates)
            return params, opt_state, packed[-1] / p

        sm = shard_map(
            body, mesh=mesh,
            in_specs=(P(), P(), batch_spec, batch_spec),
            out_specs=(P(), P(), P()),
            check_vma=False)
        return jax.jit(sm, donate_argnums=(0, 1)), qinfo

    def _pick_step(self, bx, by):
        """Packed step when it applies (fusion step tracing on, a
        declared batch-mean loss, a real mesh, the PHYSICAL batch
        dividing over it); the GSPMD step otherwise — e.g. a custom
        sum-reduction loss, a raw numpy batch whose length does not
        divide the mesh, or ``HEAT_TPU_FUSION_STEP=0``. Note a split
        ``DNDarray`` batch arrives as its padded physical array (always
        mesh-divisible) on BOTH paths — the historic semantics: any
        zero-padded tail rows participate in the loss mean identically
        packed or GSPMD."""
        from ..core import fusion

        size = self.comm.size
        if (fusion.step_enabled() and self.loss_is_batch_mean and size > 1
                and bx.ndim >= 1 and bx.shape[0] % size == 0
                and by.shape[:1] == bx.shape[:1]):
            key = (fusion.quant_key(), fusion.chunk_key(),
                   fusion.hier_key())
            if key not in self._packed_steps:
                # the KEY's tuples are also the traced wire/leg config
                # (jax traces at first dispatch; a toggle in between must
                # not change the program out from under its key)
                self._packed_steps[key] = \
                    self._build_packed_train_step(*key)
            return self._packed_steps[key][0]
        if self._train_step is None:
            self._train_step = self._build_train_step()
        return self._train_step

    def step(self, x, y) -> float:
        """One fused data-parallel training step.

        The batch arrives sharded over the mesh ('proc' = dp axis);
        gradient averaging is ONE packed all-reduce carrying every
        parameter cotangent (:meth:`_build_packed_train_step` — the
        reference's blocking per-parameter ``Allreduce(grad/size)`` hooks,
        ``data_parallel.py:223-241``, fused into a single flattened
        collective), falling back to the GSPMD-placed step for uneven
        batches or under ``HEAT_TPU_FUSION_STEP=0``.
        """
        if self.optimizer is None:
            raise RuntimeError("an optimizer is required for step()")
        if self.params is None:
            self.init(x)
        bx, by = _as_jax(x), _as_jax(y)
        step_fn = self._pick_step(bx, by)
        self.params, self.optimizer.opt_state, loss = step_fn(
            self.params, self.optimizer.opt_state, bx, by
        )
        packed = next((rec for rec in self._packed_steps.values()
                       if rec[0] is step_fn), None)
        if packed is not None:
            from ..core import fusion
            from ..utils import metrics

            metrics.inc("op_engine.fusion_step_flushes")
            fusion.tick_quant(packed[1])
        return float(loss)

    def local_loss(self, x, y) -> float:
        out = self.module.apply(self.params, _as_jax(x))
        return float(self.loss_fn(out, _as_jax(y)))


class DataParallelMultiGPU(DataParallel):
    """Two-tier DDP+DASO trainer (reference ``data_parallel.py:314-377``).

    The reference combines node-local torch DDP (NCCL allreduce every step)
    with delayed global MPI sync via DASO. TPU-native rendering on DASO's
    ``(slow=dcn) × (fast=ici)`` grid: every parameter leaf carries a leading
    node-replica axis sharded over ``dcn``; the fused train step ``vmap``s
    the local update over that axis, so each node group advances its own
    diverged copy on its own slice of the batch, while the intra-group
    gradient mean over ``ici`` is the psum GSPMD inserts (batch dims sharded
    ``(dcn, ici)``, replica axis sharded ``dcn`` → the backward's reduction
    scope is exactly one node group). DASO's schedule then reconciles the
    replicas over the slow tier (``heat_tpu.optim.DASO.step``).
    """

    def __init__(self, module, optimizer, comm=None, **kwargs):
        if not hasattr(optimizer, "global_skip"):
            raise TypeError("DataParallelMultiGPU requires a heat_tpu.optim.DASO")
        super().__init__(module, comm=comm,
                         optimizer=optimizer.local_optimizer, **kwargs)
        self.daso = optimizer

    # ------------------------------------------------------------------ #
    def init(self, sample_input) -> None:
        """Seed-unified init, then per-node replication (reference ``:108``;
        the replicas only diverge through training, like the reference's
        independently stepped node models)."""
        sample = _as_jax(sample_input)
        key = jax.random.key(self.seed)
        base = self.module.init(key, sample)
        self.params = self.daso.replicate(base)
        if self.optimizer is not None:
            self.optimizer.opt_state = jax.vmap(self.optimizer.tx.init)(self.params)

    def __call__(self, x):
        """Forward with the slow-tier-averaged parameters."""
        if self.params is None:
            self.init(x)
        xa = _as_jax(x)
        out = self.module.apply(self.daso.unreplicate(self.params), xa)
        if isinstance(x, DNDarray):
            return DNDarray.from_logical(out, x.split, x.device, x.comm)
        return out

    forward = __call__

    def _build_train_step(self):
        apply_fn = self.module.apply
        loss_fn = self.loss_fn
        tx = self.optimizer.tx

        def one_replica(params, opt_state, bx, by):
            def loss(p):
                return loss_fn(apply_fn(p, bx), by)

            lval, grads = jax.value_and_grad(loss)(params)
            updates, opt_state = tx.update(grads, opt_state, params)
            import optax

            params = optax.apply_updates(params, updates)
            return params, opt_state, lval

        vstep = jax.vmap(one_replica)

        def train_step(params, opt_state, bx, by):
            params, opt_state, lvals = vstep(params, opt_state, bx, by)
            return params, opt_state, jnp.mean(lvals)

        return jax.jit(train_step, donate_argnums=(0, 1))

    def _shard_two_tier(self, arr):
        """(B, ...) host batch → (slow, B/slow, ...) on the grid, batch
        sharded over both tiers."""
        slow = self.daso.slow_size
        arr = _as_jax(arr)
        if arr.shape[0] % slow:
            raise ValueError(
                f"batch size {arr.shape[0]} must divide by the node count {slow}")
        arr = arr.reshape((slow, arr.shape[0] // slow) + arr.shape[1:])
        return jax.device_put(
            arr, self.daso.grid.sharding(arr.ndim, dcn=0, ici=1))

    def step(self, x, y) -> float:
        """Fused two-tier local step, then the DASO slow-tier schedule (the
        reference drives the global sync from DASO's ``step``,
        ``dp_optimizer.py:730``)."""
        if self.params is None:
            self.init(_as_jax(x)[:1])
        if self._train_step is None:
            self._train_step = self._build_train_step()
        bx, by = self._shard_two_tier(x), self._shard_two_tier(y)
        self.params, self.optimizer.opt_state, loss = self._train_step(
            self.params, self.optimizer.opt_state, bx, by)
        self.params = self.daso.step(self.params)
        return float(loss)
