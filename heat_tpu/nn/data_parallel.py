"""Data-parallel training over the mesh (reference ``heat/nn/data_parallel.py``).

The reference wraps a torch module with per-parameter backward hooks that
``(I)Allreduce`` gradients over MPI (``data_parallel.py:223-297``), with
identical-seed initialization on every rank (``:108``). The TPU-native
re-design keeps the *semantics* — replicated parameters, batch sharded over
the mesh, gradients averaged across shards every step — but realizes them as
one fused jitted train step: with the batch sharded ``P('proc')`` and the
parameters replicated, XLA inserts the gradient ``psum`` over ICI
automatically and overlaps it with the backward pass (the reference's
non-blocking ``Iallreduce``+wait-handle machinery ``:175-221`` is exactly
what the XLA scheduler does for free).
"""

from __future__ import annotations

from typing import Any, Callable, Optional

import numpy as np

import jax
import jax.numpy as jnp

from ..core.communication import sanitize_comm
from ..core.dndarray import DNDarray

__all__ = ["DataParallel", "DataParallelMultiGPU"]


def _as_jax(x):
    if isinstance(x, DNDarray):
        return x.larray
    return jnp.asarray(x)


class DataParallel:
    """Data-parallel wrapper around a flax module (reference ``data_parallel.py:21``).

    Parameters
    ----------
    module : flax.linen.Module
        The network. Parameters are initialized once (single seed — the
        replicated analogue of the reference's unified-seed init) and kept
        replicated on the mesh.
    comm : TPUCommunication, optional
    optimizer : heat_tpu.optim.DataParallelOptimizer, optional
        Wraps an optax optimizer; required for :meth:`step`.
    loss_fn : callable(params, apply_fn, batch_x, batch_y) -> scalar, or
        callable(logits, y) -> scalar (detected by arity), default
        cross-entropy on integer labels.
    blocking_parameter_updates : bool
        API parity with the reference (``:52``); the XLA schedule always
        overlaps communication with compute, so both modes are the fused
        step.
    """

    def __init__(
        self,
        module,
        comm=None,
        optimizer=None,
        loss_fn: Optional[Callable] = None,
        blocking_parameter_updates: bool = False,
        seed: int = 0,
    ):
        self.module = module
        self.comm = sanitize_comm(comm)
        self.optimizer = optimizer
        self.blocking_parameter_updates = blocking_parameter_updates
        self.seed = seed
        self.params = None
        self._train_step = None
        if loss_fn is None:
            from . import functional

            loss_fn = lambda logits, y: functional.cross_entropy(logits, y)
        self.loss_fn = loss_fn
        if optimizer is not None:
            optimizer._attach(self)

    # ------------------------------------------------------------------ #
    def init(self, sample_input) -> None:
        """Initialize replicated parameters (reference seed-unified init ``:108``)."""
        sample = _as_jax(sample_input)
        key = jax.random.key(self.seed)
        self.params = self.module.init(key, sample)
        if self.optimizer is not None:
            self.optimizer.reset_state(self.params)

    def __call__(self, x):
        """Forward pass (reference forward with hook finalization ``:140-172``)."""
        if self.params is None:
            self.init(x)
        xa = _as_jax(x)
        out = self.module.apply(self.params, xa)
        if isinstance(x, DNDarray):
            return DNDarray.from_logical(out, x.split, x.device, x.comm)
        return out

    forward = __call__

    # ------------------------------------------------------------------ #
    def _build_train_step(self):
        apply_fn = self.module.apply
        loss_fn = self.loss_fn
        tx = self.optimizer.tx

        def train_step(params, opt_state, bx, by):
            def loss(p):
                logits = apply_fn(p, bx)
                return loss_fn(logits, by)

            lval, grads = jax.value_and_grad(loss)(params)
            updates, opt_state = tx.update(grads, opt_state, params)
            import optax

            params = optax.apply_updates(params, updates)
            return params, opt_state, lval

        return jax.jit(train_step, donate_argnums=(0, 1))

    def step(self, x, y) -> float:
        """One fused data-parallel training step.

        The batch arrives sharded over the mesh ('proc' = dp axis); gradient
        averaging is the GSPMD psum the partitioner inserts (the reference's
        blocking ``Allreduce(grad/size)`` hook, ``data_parallel.py:223-241``).
        """
        if self.optimizer is None:
            raise RuntimeError("an optimizer is required for step()")
        if self.params is None:
            self.init(x)
        if self._train_step is None:
            self._train_step = self._build_train_step()
        bx, by = _as_jax(x), _as_jax(y)
        self.params, self.optimizer.opt_state, loss = self._train_step(
            self.params, self.optimizer.opt_state, bx, by
        )
        return float(loss)

    def local_loss(self, x, y) -> float:
        out = self.module.apply(self.params, _as_jax(x))
        return float(self.loss_fn(out, _as_jax(y)))


class DataParallelMultiGPU(DataParallel):
    """Reference parity for the DDP+DASO wrapper (``data_parallel.py:314-377``).

    The reference combines node-local torch DDP (NCCL) with global MPI sync
    via DASO. On a TPU mesh both communication tiers ride the same XLA
    collectives; pair this wrapper with :class:`heat_tpu.optim.DASO`, which
    reconstructs the two-tier (fast axis / slow axis) schedule.
    """

    def __init__(self, module, optimizer, comm=None, **kwargs):
        super().__init__(module, comm=comm, optimizer=getattr(optimizer, "local_optimizer", optimizer), **kwargs)
        self.daso = optimizer if hasattr(optimizer, "global_skip") else None

    def step(self, x, y) -> float:
        """Fused local step, then the DASO slow-tier schedule (the reference
        drives the global sync from DASO's ``step``, ``dp_optimizer.py:730``)."""
        loss = super().step(x, y)
        if self.daso is not None:
            self.params = self.daso.step(self.params)
        return loss
