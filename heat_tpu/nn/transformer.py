"""Flagship combined-parallelism transformer LM over a MeshGrid.

One compiled ``shard_map`` train step composes every strategy in the
framework's parallelism inventory (PARITY.md §2.6):

* **dp** — batch sharded over the ``dp`` axis; gradient averaging is the
  AD transpose of the loss ``psum`` (the reference's ``nn.DataParallel``
  Allreduce, ``heat/nn/data_parallel.py:223-297``, fused into the step).
* **pp** — layers split into pipeline stages over the ``pp`` axis
  (:func:`heat_tpu.nn.parallel.pipeline_apply`, GPipe microbatch schedule).
* **tp** — attention heads and MLP features Megatron-sharded over the
  ``tp`` axis (one psum per block).
* **sp** — the token sequence sharded over the ``sp`` axis end to end;
  attention runs as an exact causal ring
  (:func:`heat_tpu.nn.attention._ring_body`: ppermute + online softmax).
* **ep** — optional Switch-MoE MLPs with experts sharded over the ``dp``
  axis (:func:`heat_tpu.nn.parallel.switch_moe`, all_to_all routing), the
  standard experts-over-dp placement.

Gradient correctness: the step runs under ``check_vma=True`` so shard_map
tracks which values are varying vs replicated along each mesh axis. That
makes every collective transpose exact — in particular, cotangents of
replicated parameters (embeddings, norm scales, each stage's weights
w.r.t. the dp/sp axes) are psum'd across exactly the axes the parameter
is replicated over, with no manual factor bookkeeping. Verified against a
dense single-device reference in ``tests/test_transformer.py``.

The reference has no transformer stack (SURVEY.md §2.6); this is the
"long-context and distributed are first-class" flagship built on the
reference's three sequence primitives (halo/ring/all-to-all).
"""

from __future__ import annotations

import math
from dataclasses import dataclass, field
from functools import partial
from typing import Any, Dict, Optional

import numpy as np

import jax
import jax.numpy as jnp
from jax import lax

from ..core._compat import shard_map
from jax.sharding import NamedSharding, PartitionSpec as P

from ..core.communication import MeshGrid
from .attention import (_ring_body, _ulysses_core, _zigzag_core,
                        local_attention, zigzag_layout, zigzag_unlayout)
from .parallel import pipeline_apply, switch_moe

__all__ = ["TransformerLM", "TransformerLMConfig"]


@dataclass
class TransformerLMConfig:
    vocab: int = 256
    d_model: int = 64
    n_heads: int = 8
    n_layers: int = 2
    d_ff: Optional[int] = None          # default 4 * d_model
    moe_experts: int = 0                # 0 = dense MLP; >0 = Switch-MoE
    capacity_factor: float = 1.25
    n_micro: int = 1                    # microbatches for the pp schedule
    compute_dtype: Any = jnp.float32    # bf16 on real TPUs for MXU rate
    init_scale: float = 0.02
    attn_schedule: str = "ring"         # "ring" | "zigzag" (load-balanced
                                        # causal ring) | "ulysses" (all_to_all
                                        # head-parallel; local heads % sp == 0)
    rope: bool = True                   # rotary position embeddings on q/k
    rope_theta: float = 10000.0
    remat: bool = False                 # jax.checkpoint each layer: trade
                                        # recompute FLOPs for activation HBM

    def __post_init__(self):
        if self.d_ff is None:
            self.d_ff = 4 * self.d_model
        if self.d_model % self.n_heads:
            raise ValueError("d_model must be divisible by n_heads")
        if self.attn_schedule not in ("ring", "zigzag", "ulysses"):
            raise ValueError(
                f"attn_schedule must be 'ring', 'zigzag' or 'ulysses', got "
                f"{self.attn_schedule!r}")
        if self.rope and self.head_dim % 2:
            raise ValueError(
                f"rope needs an even head_dim, got {self.head_dim}")

    @property
    def head_dim(self) -> int:
        return self.d_model // self.n_heads


def _rmsnorm(x, scale):
    var = jnp.mean(jnp.square(x.astype(jnp.float32)), axis=-1, keepdims=True)
    return (x * jax.lax.rsqrt(var + 1e-6)).astype(x.dtype) * scale


def rope_apply(x, pos, theta: float = 10000.0):
    """Rotary position embedding (half-split convention) on ``(mb, S, H,
    Dh)`` with GLOBAL token positions ``pos`` of shape ``(S,)`` — or
    ``(mb, S)`` when every batch row sits at its own position (the
    serving decode engine: one slot per row, each mid-stream). Positions
    are supplied explicitly because under sequence parallelism the local
    block's positions depend on the layout: contiguous split gives
    ``r*S_local + arange``, the zigzag layout two chunk-offset ranges."""
    half = x.shape[-1] // 2
    freq = theta ** (-jnp.arange(half, dtype=jnp.float32) / half)
    ang = pos.astype(jnp.float32)[..., None] * freq  # (S, half) | (B, S, half)
    if ang.ndim == 2:
        ang = ang[None]                              # shared across the batch
    cos = jnp.cos(ang)[:, :, None, :]
    sin = jnp.sin(ang)[:, :, None, :]
    x1, x2 = x[..., :half].astype(jnp.float32), x[..., half:].astype(jnp.float32)
    out = jnp.concatenate([x1 * cos - x2 * sin, x1 * sin + x2 * cos], axis=-1)
    return out.astype(x.dtype)


class TransformerLM:
    """Causal LM with dp x pp x tp x sp (x ep) over a 4-axis MeshGrid.

    ``grid`` must have axes named ``("dp", "pp", "tp", "sp")`` (any sizes,
    1 allowed). Parameters are held as global ``jax.Array``s with
    ``NamedSharding``s; stage weights carry a leading ``pp`` axis, head /
    feature axes shard over ``tp``, expert axes over ``dp``.
    """

    AXES = ("dp", "pp", "tp", "sp")

    def __init__(self, grid: MeshGrid, config: TransformerLMConfig):
        names = tuple(grid.axis_names)
        # an optional LEADING "dcn" axis declares the slow inter-host
        # tier of a 2-level dp grid (dcn x dp both shard the batch):
        # parameters stay replicated over it (the specs never name it),
        # and the packed train step's gradient all-reduce decomposes
        # hierarchically — reduce-scatter inside the fast tier,
        # all-reduce of the 1/p_ici shard across dcn, all-gather back
        # (heat_tpu.core.fusion.packed_psum, HEAT_TPU_HIER)
        if names == self.AXES:
            self._has_dcn = False
        elif names == ("dcn",) + self.AXES:
            self._has_dcn = True
        else:
            raise ValueError(
                f"grid axes must be {self.AXES} (optionally with a "
                f"leading 'dcn' tier axis), got {grid.axis_names}")
        self.grid = grid
        self.cfg = config
        c = config
        self.dcn = grid.mesh.shape["dcn"] if self._has_dcn else 1
        self.pp = grid.mesh.shape["pp"]
        self.tp = grid.mesh.shape["tp"]
        self.dp = grid.mesh.shape["dp"]
        self.sp = grid.mesh.shape["sp"]
        if c.n_layers % self.pp:
            raise ValueError(f"n_layers ({c.n_layers}) must divide over pp ({self.pp})")
        if c.n_heads % self.tp:
            raise ValueError(f"n_heads ({c.n_heads}) must divide over tp ({self.tp})")
        if c.d_ff % self.tp:
            raise ValueError(f"d_ff ({c.d_ff}) must divide over tp ({self.tp})")
        if c.moe_experts and c.moe_experts % self.dp:
            raise ValueError(
                f"moe_experts ({c.moe_experts}) must divide over dp ({self.dp}) "
                "(experts are sharded over the dp axis)")
        if (c.attn_schedule == "ulysses" and self.sp > 1
                and (c.n_heads // self.tp) % self.sp):
            raise ValueError(
                f"ulysses schedule needs local heads ({c.n_heads}//{self.tp}"
                f"={c.n_heads // self.tp}) divisible by sp ({self.sp})")
        self.layers_per_stage = c.n_layers // self.pp
        self.mesh_size = self.dcn * self.dp * self.pp * self.tp * self.sp
        self._step_cache: Dict = {}

    @property
    def dp_world(self) -> int:
        """Total data-parallel replication: the dp axis times the
        optional dcn tier axis above it (batch rows shard over both)."""
        return self.dcn * self.dp

    # ------------------------------------------------------------- #
    # parameters                                                    #
    # ------------------------------------------------------------- #

    def param_specs(self) -> Dict[str, Any]:
        c, Ls = self.cfg, self.layers_per_stage
        stages = {
            "ln1": P("pp", None, None),
            # (pp, Ls, D, 3, H, Dh): heads sharded over tp
            "wqkv": P("pp", None, None, None, "tp", None),
            # (pp, Ls, H, Dh, D): row-parallel output projection
            "wproj": P("pp", None, "tp", None, None),
            "ln2": P("pp", None, None),
        }
        if c.moe_experts:
            stages.update({
                "router": P("pp", None, None, None),
                # experts over dp AND the expert hidden dim over tp, so the
                # expert FLOPs split over tp like the dense branch (psum in
                # _block) instead of replicating the full FFN per tp rank
                "w_up": P("pp", None, "dp", None, "tp"),    # (pp, Ls, E, D, F)
                "w_down": P("pp", None, "dp", "tp", None),  # (pp, Ls, E, F, D)
            })
        else:
            stages.update({
                "w_up": P("pp", None, None, "tp"),          # (pp, Ls, D, F)
                "w_down": P("pp", None, "tp", None),        # (pp, Ls, F, D)
            })
        return {
            "embed": P(None, None),
            "final_ln": P(None),
            "unembed": P(None, None),
            "stages": stages,
        }

    def shard_params(self, params) -> Dict[str, Any]:
        """Place a (host or differently-placed) parameter tree onto this
        grid's shardings — e.g. after ``load_checkpoint``, whose restored
        leaves are host arrays."""
        shardings = jax.tree.map(
            lambda s: NamedSharding(self.grid.mesh, s), self.param_specs(),
            is_leaf=lambda s: isinstance(s, P))
        # device_put handles the pytree-of-shardings form natively and
        # batches the transfers (one placement, not one per leaf)
        return jax.device_put(params, shardings)

    def init(self, seed: int = 0) -> Dict[str, Any]:
        c, Ls, pp = self.cfg, self.layers_per_stage, self.pp
        H, Dh, D, F, V = c.n_heads, c.head_dim, c.d_model, c.d_ff, c.vocab
        rng = np.random.default_rng(seed)
        s = c.init_scale

        def norm(*shape):
            return (s * rng.standard_normal(shape)).astype(np.float32)

        stages = {
            "ln1": np.ones((pp, Ls, D), np.float32),
            "wqkv": norm(pp, Ls, D, 3, H, Dh),
            "wproj": norm(pp, Ls, H, Dh, D),
            "ln2": np.ones((pp, Ls, D), np.float32),
        }
        if c.moe_experts:
            E = c.moe_experts
            stages["router"] = norm(pp, Ls, D, E)
            stages["w_up"] = norm(pp, Ls, E, D, F)
            stages["w_down"] = norm(pp, Ls, E, F, D)
        else:
            stages["w_up"] = norm(pp, Ls, D, F)
            stages["w_down"] = norm(pp, Ls, F, D)
        host = {
            "embed": norm(V, D),
            "final_ln": np.ones((D,), np.float32),
            "unembed": norm(D, V),
            "stages": stages,
        }
        return self.shard_params(host)

    # ------------------------------------------------------------- #
    # the per-device program                                        #
    # ------------------------------------------------------------- #

    def _block(self, p, x, sp_comm, pos):
        """One transformer layer on a local microbatch (mb, S_local, D).
        ``pos``: global positions of this device's S_local tokens (layout-
        aware, computed once per forward in ``_loss_device``)."""
        c = self.cfg
        Hs = c.n_heads // self.tp
        mb, S_local, D = x.shape

        p = self._cast_params(p)
        q, k, v = self._qkv(p, x, pos)
        scale = 1.0 / math.sqrt(c.head_dim)
        if c.attn_schedule == "zigzag" and sp_comm.size > 1:
            # load-balanced causal ring: every sp device does identical live
            # work per step. The token stream is ALREADY in zigzag layout —
            # _loss_device relayouts once after embedding and inverts once
            # before the loss, so each layer pays zero layout ppermutes
            # (every non-attention op in the block is positionwise)
            attn = _zigzag_core(q, k, v, comm=sp_comm, scale=scale)
        elif c.attn_schedule == "ulysses" and sp_comm.size > 1:
            # all_to_all head-parallel: two collectives per layer instead of
            # sp-1 ppermute steps — often wins at moderate S on fast ICI
            attn = _ulysses_core(q, k, v, comm=sp_comm, scale=scale,
                                 causal=True)
        else:
            attn = _ring_body(q, k, v, comm=sp_comm, scale=scale, causal=True)
        x = self._attn_residual(p, x, attn)

        m_in = _rmsnorm(x, p["ln2"])
        if c.moe_experts:
            flat = m_in.reshape(mb * S_local, D)
            # expert hidden dim is tp-sharded: partial down-projections sum
            # over tp (one psum, mirroring the dense Megatron block)
            moe_out = self._psum_tp(
                switch_moe(
                    flat, p["router"], p["w_up"], p["w_down"], axis="dp",
                    capacity_factor=c.capacity_factor))
            return x + moe_out.reshape(mb, S_local, D)
        return self._dense_mlp_residual(p, x, m_in)

    # shared layer math — _block (training), the prefill pass and the
    # cached decode step (generate) all call these, so an architecture
    # change lands everywhere at once

    def _cast_params(self, p):
        """Mixed precision: master params stay f32 in the optimizer; compute
        runs in compute_dtype (bf16 on real TPUs for MXU rate). Without this
        cast f32 params silently promote every activation back to f32 and
        compute_dtype never takes effect."""
        c = self.cfg
        if c.compute_dtype == jnp.float32:
            return p
        return jax.tree.map(
            lambda a: a.astype(c.compute_dtype)
            if jnp.issubdtype(a.dtype, jnp.floating) else a, p)

    def _qkv(self, p, x, pos):
        """Pre-norm qkv projection for the local head subset, with rotary
        rotation by the GLOBAL positions ``pos``."""
        c = self.cfg
        a_in = _rmsnorm(x, p["ln1"])
        qkv = jnp.einsum("bsd,dohk->bsohk", a_in, p["wqkv"])
        q, k, v = qkv[:, :, 0], qkv[:, :, 1], qkv[:, :, 2]
        if c.rope:
            q = rope_apply(q, pos, c.rope_theta)
            k = rope_apply(k, pos, c.rope_theta)
        return q, k, v

    def _psum_tp(self, x, wire=None):
        """The Megatron-block tp reduction — skipped on tp=1 grids when
        the jax has no vma tracking: a size-1-axis psum is a value
        identity but still lowers to a (singleton-group) all-reduce pair
        through forward+backward. Under vma tracking the identity psum
        stays — ``check_vma=True`` needs it to clear the tp-varying type
        (the SAME capability gate as ``pipeline_apply``'s pp==1 branch:
        :func:`heat_tpu.nn.parallel.vma_capable`).

        ``wire``: a ``(quant_key, chunk_key, hier_key)`` triple pinned by
        a builder that cache-keyed on it (the serving decode engine) —
        the psum then rides :func:`heat_tpu.core.fusion.packed_psum` so
        the opt-in wire codecs apply; the exact-codec emission is
        bitwise the plain ``lax.psum`` (PR 4 probe). Wire bodies are
        always ``check_vma=False``, so tp=1 emits nothing."""
        if wire is not None:
            if self.tp <= 1:
                return x
            from ..core import fusion

            qk, ck, hk = wire
            return fusion.packed_psum([x], ("tp",), quant=qk, chunks=ck,
                                      hier=hk)[0]
        from .parallel import vma_capable

        if self.tp > 1 or vma_capable():
            return lax.psum(x, "tp")
        return x

    def _attn_residual(self, p, x, attn, wire=None):
        """Row-parallel output projection (one tp psum) + residual."""
        return x + self._psum_tp(
            jnp.einsum("bshk,hkd->bsd", attn, p["wproj"]), wire=wire)

    def _dense_mlp_residual(self, p, x, m_in, wire=None):
        h = jax.nn.gelu(m_in @ p["w_up"])
        return x + self._psum_tp(h @ p["w_down"], wire=wire)

    def _head(self, params, h):
        """Final norm + unembed; logits upcast to f32 only after the GEMM —
        an f32 norm scale would push the largest matmul off the bf16 path."""
        c = self.cfg
        h = _rmsnorm(h, params["final_ln"].astype(c.compute_dtype))
        return (h @ params["unembed"].astype(c.compute_dtype)).astype(jnp.float32)

    def _forward_device(self, params, toks):
        """Per-device forward: toks (B_local, S_local) -> f32 logits
        (B_local, S_local, vocab). Shared by the training loss and the
        serving forward (:meth:`logits_fn`)."""
        c = self.cfg
        sp_comm = self.grid.axis("sp")
        B_local, S_local = toks.shape
        if B_local % c.n_micro:
            raise ValueError(
                f"local batch ({B_local}) must divide into n_micro ({c.n_micro})")
        mb = B_local // c.n_micro

        x = params["embed"][toks].astype(c.compute_dtype)
        zigzag = c.attn_schedule == "zigzag" and sp_comm.size > 1
        sp_idx = lax.axis_index("sp")
        if zigzag:
            # one layout round-trip per forward: into zigzag here, back to
            # contiguous before the loss — the layers in between are either
            # positionwise (layout-agnostic) or zigzag-aware (_zigzag_core)
            x = zigzag_layout(x, sp_comm)
            # global positions of the zigzag-resident tokens: chunk sp_idx
            # and chunk 2n-1-sp_idx
            half = S_local // 2
            n_sp = sp_comm.size
            pos = jnp.concatenate([
                sp_idx * half + jnp.arange(half),
                (2 * n_sp - 1 - sp_idx) * half + jnp.arange(half),
            ])
        else:
            pos = sp_idx * S_local + jnp.arange(S_local)
        x_micro = x.reshape(c.n_micro, mb, S_local, c.d_model)

        stage_params = jax.tree.map(lambda a: a[0], params["stages"])

        def block(p_l, xm):
            return self._block(p_l, xm, sp_comm, pos)

        if c.remat:
            # rematerialise each layer on the backward pass: activation HBM
            # drops from O(n_layers) to O(1) blocks per stage at the cost of
            # one extra forward — the standard deep-model memory trade
            # (jax.checkpoint per the TPU HBM playbook)
            # prevent_cse=False: every call site is inside a lax.scan (the
            # pipeline tick / microbatch scan), where the CSE barriers the
            # default inserts are documented as unnecessary overhead
            block = jax.checkpoint(block, prevent_cse=False)

        def stage_fn(sp_params, xm):
            for l in range(self.layers_per_stage):
                p_l = jax.tree.map(lambda a: a[l], sp_params)
                xm = block(p_l, xm)
            return xm

        out = pipeline_apply(stage_fn, stage_params, x_micro, axis="pp")
        h = out.reshape(B_local, S_local, c.d_model)
        if zigzag:
            h = zigzag_unlayout(h, sp_comm)
        return self._head(params, h)

    def _local_loss_device(self, params, toks):
        """Per-device code: toks (B_local, S_local) -> this device's SHARE
        of the global loss (local masked NLL sum over the static global
        count). ``psum(local, ("dp", "sp")) == global loss`` — the
        :meth:`_loss_device` form the check_vma path compiles — and
        because the share is collective-free past the forward, the packed
        train step can differentiate it per device and combine every
        parameter cotangent in ONE flattened all-reduce
        (:func:`heat_tpu.core.fusion.packed_psum`)."""
        B_local, S_local = toks.shape
        logits = self._forward_device(params, toks)

        # next-token targets across the sharded sequence: local shift plus
        # the neighbour shard's first token via ppermute (the halo pattern,
        # reference dndarray.py:360-433)
        sp, sp_axis = self.sp, "sp"
        first = toks[:, :1]
        if sp > 1:
            nxt = lax.ppermute(
                first, sp_axis, [(i, (i - 1) % sp) for i in range(sp)])
        else:
            nxt = first
        targets = jnp.concatenate([toks[:, 1:], nxt], axis=1)
        logp = jax.nn.log_softmax(logits, axis=-1)
        nll = -jnp.take_along_axis(logp, targets[..., None], axis=-1)[..., 0]
        # the global last position has no next token
        is_last_shard = lax.axis_index(sp_axis) == sp - 1
        pos_mask = jnp.arange(S_local) < S_local - 1
        mask = jnp.where(is_last_shard, pos_mask, jnp.ones_like(pos_mask))
        mask = jnp.broadcast_to(mask[None, :], nll.shape).astype(nll.dtype)

        # the count is static — B_global rows each lose one position —
        # which also keeps it out of the vma system (a mask-sum would be
        # invarying over dp and unreducible there)
        count = B_local * self.dp_world * (S_local * sp - 1)
        return jnp.sum(nll * mask) / count

    def _data_axes(self):
        """The data axes (the loss psum scope): dp and sp, plus the dcn
        tier axis when the grid declares one."""
        return (("dcn", "dp", "sp") if self._has_dcn else ("dp", "sp"))

    def _loss_device(self, params, toks):
        """Per-device code: toks (B_local, S_local) -> replicated global loss."""
        return lax.psum(self._local_loss_device(params, toks),
                        self._data_axes())

    # ------------------------------------------------------------- #
    # jitted steps                                                  #
    # ------------------------------------------------------------- #

    def _data_spec(self):
        if self._has_dcn:
            # batch rows shard over BOTH data-parallel tiers (dcn-major,
            # like jax.devices() orders a real pod's hosts)
            return P(("dcn", "dp"), "sp")
        return P("dp", "sp")

    def shard_batch(self, toks: np.ndarray) -> jax.Array:
        """Place a (B, S) int32 token batch dp x sp sharded on the grid."""
        return jax.device_put(
            jnp.asarray(toks, jnp.int32),
            NamedSharding(self.grid.mesh, self._data_spec()))

    @property
    def packed_step_supported(self) -> bool:
        """Whether the packed-collective train step applies to this grid:
        pp == tp == 1 and a dense MLP. Those are exactly the layouts
        whose forward has no collective the ``check_vma=False`` AD
        transpose mishandles — ppermute/all_to_all (the sp attention
        schedules) transpose exactly without replication typing, while a
        forward tp psum or the pipeline's masked psum broadcast needs vma
        tracking for factor-free cotangents of replicated parameters."""
        return self.pp == 1 and self.tp == 1 and not self.cfg.moe_experts

    def _batch_axes(self):
        """Non-trivial data axes — the reduction scope of the packed
        gradient all-reduce (empty on a 1-device grid: no collective).
        The dcn tier axis leads: packed_psum's tier split sees it as the
        slow tier and dp/sp as the fast one."""
        return tuple(a for a, n in (("dcn", self.dcn), ("dp", self.dp),
                                    ("sp", self.sp))
                     if n > 1)

    def _packed_loss_and_grad_body(self, qinfo=None, quant=None,
                                   chunks=None, hier=None):
        """Per-device (params, toks) -> (loss, grads) with every gradient
        cotangent — and the loss — combined in ONE flattened all-reduce:
        local value_and_grad of the device's loss share, then
        :func:`heat_tpu.core.fusion.packed_psum` over the data axes (the
        generalized-allreduce packing, arXiv:2004.09362), instead of the
        one-psum-per-parameter GSPMD emits for the transposed broadcast.
        Under ``HEAT_TPU_QUANT_COLLECTIVES`` the qualifying gradient
        payloads ride the quantized exchange (the scalar loss is below
        the size floor and stays exact); ``qinfo`` collects the rewrite
        counts at trace time for the step wrapper's counters; ``quant``
        and ``chunks`` pin the configurations the builder cache-keyed on
        (jax traces at first dispatch — a codec or chunk-count toggle in
        between must not change the traced wire format or leg structure
        out from under the key)."""
        from ..core import fusion

        axes = self._batch_axes()

        def body(params, toks):
            if qinfo is not None:
                fusion.reset_qinfo(qinfo)
            lval, grads = jax.value_and_grad(
                self._local_loss_device)(params, toks)
            leaves, treedef = jax.tree_util.tree_flatten(grads)
            packed = fusion.packed_psum(leaves + [lval], axes, qinfo=qinfo,
                                        quant=quant, chunks=chunks,
                                        hier=hier)
            return packed[-1], jax.tree_util.tree_unflatten(
                treedef, packed[:-1])

        return body

    def loss_and_grad_fn(self):
        """jitted (params, toks) -> (loss, grads) over the full grid.

        On grids the packed step supports (and with
        ``HEAT_TPU_FUSION_STEP`` on) the gradient collectives are packed
        into one flattened all-reduce under ``check_vma=False``; other
        grids keep the check_vma path (vma tracking makes every
        collective transpose exact for pipeline/tensor parallelism)."""
        from ..core import fusion

        packed = self.packed_step_supported and fusion.step_enabled()
        # the quant codec changes the packed program's collective wire
        # format, the chunk count its leg structure and the hier config
        # its collective decomposition, so all three key the cache —
        # toggling compiles a sibling program instead of poisoning the
        # exact/unchunked/flat one (the legacy key stays 2-tuple: the
        # check_vma path never quantizes, chunks or decomposes)
        qk = fusion.quant_key()
        ck = fusion.chunk_key()
        hk = fusion.hier_key()
        key = ("loss_and_grad", True, qk, ck, hk) if packed \
            else ("loss_and_grad", False)
        fn = self._step_cache.get(key)
        if fn is None:
            specs = self.param_specs()
            if packed:
                qinfo = {}
                sm = shard_map(
                    self._packed_loss_and_grad_body(qinfo=qinfo, quant=qk,
                                                    chunks=ck, hier=hk),
                    mesh=self.grid.mesh,
                    in_specs=(specs, self._data_spec()),
                    out_specs=(P(), specs),
                    check_vma=False)
                jitted = jax.jit(sm)

                def fn(params, toks, _jitted=jitted, _qinfo=qinfo):
                    out = _jitted(params, toks)
                    # per-dispatch counters, like the step wrappers —
                    # runtime_stats must show quantization ran on THIS
                    # surface too (doc/fusion.md counter contract)
                    fusion.tick_quant(_qinfo)
                    return out

                fn.lower = jitted.lower
                self._step_cache[key] = fn
                return fn
            else:
                def body(params, toks):
                    return jax.value_and_grad(self._loss_device)(params, toks)

                # check_vma=True: replication (varying-across-mesh-axes)
                # types are tracked, so collective transposes are exact —
                # gradients of replicated parameters are psum'd across
                # exactly the axes they are replicated over, with no
                # seed-count factors
                sm = shard_map(
                    body, mesh=self.grid.mesh,
                    in_specs=(specs, self._data_spec()),
                    out_specs=(P(), specs),
                    check_vma=True)
            fn = jax.jit(sm)
            self._step_cache[key] = fn
        return fn

    def logits_fn(self):
        """jitted ``(params, toks) -> (B, S, vocab) f32 logits`` over the
        full grid — the serving forward (``heat_tpu.serve.adapters``).

        Same per-device program as the training loss up to the head
        (:meth:`_forward_device`), compiled once and cached; runs with
        ``check_vma=False`` (inference needs no replication-type tracking,
        and the forward then traces on every supported jax)."""
        key = "logits"
        fn = self._step_cache.get(key)
        if fn is None:
            sm = shard_map(
                self._forward_device, mesh=self.grid.mesh,
                in_specs=(self.param_specs(), self._data_spec()),
                out_specs=P("dp", "sp", None),
                check_vma=False)
            fn = jax.jit(sm)
            self._step_cache[key] = fn
        return fn

    def make_train_step(self, tx):
        """jitted (params, opt_state, toks) -> (params, opt_state, loss)
        with an optax transform ``tx``, parameter/optimizer state donated.

        On grids :attr:`packed_step_supported` covers (and with
        ``HEAT_TPU_FUSION_STEP`` on) the WHOLE step — forward, backward,
        packed gradient all-reduce, optimizer update — is one
        ``shard_map`` program: the collective count is the packed plan's
        (one flattened all-reduce over the data axes carrying every
        parameter cotangent plus the loss), not one-per-parameter, and
        repeat calls are a single donated program dispatch with zero host
        round-trips. Other grids compose the check_vma loss-and-grad
        program with a GSPMD optimizer update under one outer jit (the
        historic path)."""
        import optax

        from ..core import fusion

        if self.packed_step_supported and fusion.step_enabled():
            specs = self.param_specs()
            qinfo = {}
            lg_body = self._packed_loss_and_grad_body(
                qinfo=qinfo, quant=fusion.quant_key(),
                chunks=fusion.chunk_key(), hier=fusion.hier_key())

            def body(params, opt_state, toks):
                loss, grads = lg_body(params, toks)
                updates, opt_state = tx.update(grads, opt_state, params)
                return optax.apply_updates(params, updates), opt_state, loss

            # opt_state rides as a replicated pytree (P() spec prefix):
            # the update math is identical on every device, like params
            sm = shard_map(
                body, mesh=self.grid.mesh,
                in_specs=(specs, P(), self._data_spec()),
                out_specs=(specs, P(), P()),
                check_vma=False)
            jitted = jax.jit(sm, donate_argnums=(0, 1))

            def step(params, opt_state, toks):
                out = jitted(params, opt_state, toks)
                # the model-level fused step counts like a traced step
                # (DataParallel's packed path does the same), so the
                # ladder's per-test fusion_step_flushes line shows the
                # packed path actually ran
                from ..utils import metrics

                metrics.inc("op_engine.fusion_step_flushes")
                fusion.tick_quant(qinfo)
                return out

            # the audit/steady-state surface of the underlying program
            step.lower = jitted.lower
            if hasattr(jitted, "_cache_size"):
                step._cache_size = jitted._cache_size
            return step

        lg = self.loss_and_grad_fn()

        # donate params/opt_state: both are consumed and re-emitted every
        # step, so XLA updates them in place — halves their HBM footprint
        # (matches nn/data_parallel.py's train step)
        @partial(jax.jit, donate_argnums=(0, 1))
        def step(params, opt_state, toks):
            loss, grads = lg(params, toks)
            updates, opt_state = tx.update(grads, opt_state, params)
            return optax.apply_updates(params, updates), opt_state, loss

        return step

    # ------------------------------------------------------------- #
    # generation (KV-cached autoregressive decode)                  #
    # ------------------------------------------------------------- #
    # the cache-attention bodies below are shared by generate()'s
    # compiled batch program AND the serving continuous-batching engine
    # (heat_tpu.serve.decode.DecodeEngine) — an architecture change
    # lands in both decoders at once, like _block/_forward_device for
    # training and serving forwards

    PROMPT_BUCKET_MIN = 8

    @classmethod
    def prompt_bucket(cls, s0: int) -> int:
        """The prompt-length bucket: smallest power of two >= ``s0``
        (floored at :data:`PROMPT_BUCKET_MIN`) — the Pow2Buckets ladder
        applied to sequence length. Prompts pad onto the bucket so one
        compiled program serves every prompt length in it; the padded
        rows' K/V stay masked (``col < n_valid``) until overwritten."""
        s0 = int(s0)
        if s0 < 1:
            raise ValueError(f"prompt length must be >= 1, got {s0}")
        return max(cls.PROMPT_BUCKET_MIN, 1 << (s0 - 1).bit_length())

    def check_decode_grid(self) -> None:
        """Decode is token-recurrent: a pipelined or sequence-sharded
        layout would idle on the single live token, and MoE routing at
        S=1 degenerates. Shared guard for generate() and DecodeEngine."""
        if self.pp != 1 or self.sp != 1:
            raise ValueError(
                "generate requires a pp=1, sp=1 grid (token-recurrent "
                "decode); use dp x tp for inference")
        if self.cfg.moe_experts:
            raise NotImplementedError("generate supports the dense MLP only")

    def _attn_from_cache(self, q, ck, cv, upto):
        """q (Bl, 1, Hs, Dh) against cached rows < ``upto`` (a scalar, or
        a (Bl,) vector when every row is at its own decode depth — the
        serving engine's per-slot live positions)."""
        s = jnp.einsum("bqhd,bshd->bhqs", q.astype(jnp.float32),
                       ck.astype(jnp.float32)) / math.sqrt(self.cfg.head_dim)
        col = jnp.arange(ck.shape[1])[None, None, None, :]
        lim = upto if jnp.ndim(upto) == 0 else upto[:, None, None, None]
        s = jnp.where(col < lim, s, -jnp.inf)
        w = jax.nn.softmax(s, axis=-1)
        out = jnp.einsum("bhqs,bshd->bqhd", w, cv.astype(jnp.float32))
        return out.astype(q.dtype)

    def _cache_layer_step(self, p_l, x, ck, cv, pos, wire=None):
        """One block on a single-token batch (Bl, 1, D): write this
        token's K/V at per-row cache position ``pos`` ((Bl,) int32) and
        attend rows < pos+1. ``generate`` passes a uniform ``pos`` (the
        whole batch at step t); the DecodeEngine passes each slot's own
        position. Rows whose position the caller does not advance (dead
        slots) just overwrite the same masked row — harmless by the
        col < upto discipline."""
        Bl = x.shape[0]
        q, k, v = self._qkv(p_l, x, pos[:, None])
        ck = ck.at[jnp.arange(Bl), pos].set(k[:, 0].astype(ck.dtype))
        cv = cv.at[jnp.arange(Bl), pos].set(v[:, 0].astype(cv.dtype))
        x = self._attn_residual(
            p_l, x, self._attn_from_cache(q, ck, cv, pos + 1), wire=wire)
        x = self._dense_mlp_residual(
            p_l, x, _rmsnorm(x, p_l["ln2"]), wire=wire)
        return x, ck, cv

    def _prompt_kv_logits(self, params, toks, n_valid, wire=None):
        """Padded-prompt prefill forward: ``toks`` (Bl, Sp) int32 with
        rows >= ``n_valid`` (a traced scalar) being pad. Returns per-layer
        K/V lists (each (Bl, Sp, Hs, Dh), post-RoPE — each row rotated by
        its absolute position exactly as in training) and the f32 logits
        at position ``n_valid - 1``. Causal attention never reads a later
        column, so valid rows are exactly the unpadded forward's; padded
        rows carry garbage the caller must keep masked (col < upto) until
        its own decode writes overwrite them."""
        c = self.cfg
        dtype = c.compute_dtype
        stage_params = jax.tree.map(lambda a: a[0], params["stages"])
        Sp = toks.shape[1]
        x = params["embed"][toks].astype(dtype)
        pos0 = jnp.arange(Sp)
        ks, vs = [], []
        for l in range(c.n_layers):
            p_l = self._cast_params(
                jax.tree.map(lambda a: a[l], stage_params))
            q, k, v = self._qkv(p_l, x, pos0)
            ks.append(k.astype(dtype))
            vs.append(v.astype(dtype))
            attn = jnp.moveaxis(local_attention(
                jnp.moveaxis(q, 2, 1), jnp.moveaxis(k, 2, 1),
                jnp.moveaxis(v, 2, 1), causal=True), 1, 2)
            x = self._attn_residual(p_l, x, attn, wire=wire)
            x = self._dense_mlp_residual(
                p_l, x, _rmsnorm(x, p_l["ln2"]), wire=wire)
        h_last = lax.dynamic_slice_in_dim(x, n_valid - 1, 1, axis=1)
        return ks, vs, self._head(params, h_last)[:, 0]

    def generate(self, params, prompts, max_new_tokens: int,
                 temperature: float = 0.0, seed: int = 0):
        """Autoregressive decode with a per-layer KV cache.

        ``prompts``: ``(B, S0)`` int tokens; returns ``(B, S0 +
        max_new_tokens)`` (prompt included). ``temperature=0`` is greedy,
        otherwise softmax sampling at that temperature. Runs on the model's
        grid with the batch sharded over dp and heads/features over tp;
        decode is a single compiled program (prefill pass + a
        ``lax.scan`` over steps). Requires ``pp == sp == 1`` (decode is
        token-recurrent: a pipelined or sequence-sharded layout would idle
        on the single live token) and a dense MLP (no MoE routing at S=1).

        The prompt length is BUCKETED (:meth:`prompt_bucket`): prompts
        pad to the power-of-two ladder and the true length rides as a
        traced scalar, so repeated calls with varying ``S0`` share one
        compiled program per ``(B, bucket, max_new_tokens, temperature)``
        instead of recompiling per exact prompt length (program-key
        hygiene; steady-state compiles 0, pinned in
        ``tests/test_serve_decode.py``).

        K/V are cached post-RoPE, so each cache row is rotated by its own
        absolute position exactly as in the training forward.
        """
        c = self.cfg
        self.check_decode_grid()
        prompts = jnp.asarray(prompts, jnp.int32)
        B, S0 = prompts.shape
        if B % self.dp_world:
            raise ValueError(
                f"prompt batch ({B}) must divide over the data-parallel "
                f"world ({self.dp_world})")
        max_new_tokens = int(max_new_tokens)
        if max_new_tokens < 1:
            raise ValueError(f"max_new_tokens must be >= 1, got {max_new_tokens}")
        Sb = self.prompt_bucket(S0)
        S_max = Sb + max_new_tokens

        def body(params, toks, n_valid, key):
            Bl = toks.shape[0]
            # independent sampling noise per data-parallel shard — a
            # replicated key would draw IDENTICAL continuations for equal
            # logits across the batch shards (both dp tiers count)
            dp_idx = lax.axis_index("dp")
            if self._has_dcn:
                dp_idx = lax.axis_index("dcn") * self.dp + dp_idx
            key = jax.random.fold_in(key, dp_idx)
            stage_params = jax.tree.map(lambda a: a[0], params["stages"])
            dtype = c.compute_dtype
            Hs = c.n_heads // self.tp
            caches_k = jnp.zeros((c.n_layers, Bl, S_max, Hs, c.head_dim),
                                 dtype)
            caches_v = jnp.zeros_like(caches_k)

            # ---- prefill: causal pass over the padded prompt ---- #
            ks, vs, logits0 = self._prompt_kv_logits(params, toks, n_valid)
            for l in range(c.n_layers):
                caches_k = caches_k.at[l, :, :Sb].set(ks[l])
                caches_v = caches_v.at[l, :, :Sb].set(vs[l])

            def sample(logits, key):
                if temperature == 0.0:
                    return jnp.argmax(logits, axis=-1).astype(jnp.int32)
                return jax.random.categorical(
                    key, logits / temperature, axis=-1).astype(jnp.int32)

            key0, key = jax.random.split(key)
            first = sample(logits0, key0)

            # ---- decode scan ---- #
            def step(carry, key_t):
                caches_k, caches_v, tok, t = carry
                x = params["embed"][tok].astype(dtype)[:, None, :]
                pos = jnp.full((Bl,), t, jnp.int32)
                new_k, new_v = caches_k, caches_v
                for l in range(c.n_layers):
                    p_l = self._cast_params(
                        jax.tree.map(lambda a: a[l], stage_params))
                    xl, ckl, cvl = self._cache_layer_step(
                        p_l, x, new_k[l], new_v[l], pos)
                    x = xl
                    new_k = new_k.at[l].set(ckl)
                    new_v = new_v.at[l].set(cvl)
                logits = self._head(params, x)[:, 0]
                nxt = sample(logits, key_t)
                return (new_k, new_v, nxt, t + 1), tok

            # first came from the prefill; N-1 scan steps yield the rest
            # (each step consumes the previous token and emits the next)
            keys = jax.random.split(key, max_new_tokens)[1:]
            (_, _, last, _), toks_out = lax.scan(
                step, (caches_k, caches_v, first, n_valid), keys)
            # toks_out: (N-1, Bl) tokens FED at each step; append the final
            return jnp.concatenate(
                [jnp.swapaxes(toks_out, 0, 1), last[:, None]], axis=1)

        data_spec = P(("dcn", "dp"), None) if self._has_dcn \
            else P("dp", None)
        cache_key = ("generate", B, Sb, max_new_tokens, float(temperature))
        fn = self._step_cache.get(cache_key)
        if fn is None:
            fn = jax.jit(shard_map(
                body, mesh=self.grid.mesh,
                in_specs=(self.param_specs(), data_spec, P(), P()),
                out_specs=data_spec, check_vma=False))
            self._step_cache[cache_key] = fn
        padded = jnp.pad(prompts, ((0, 0), (0, Sb - S0)))
        toks_sharded = jax.device_put(
            padded, NamedSharding(self.grid.mesh, data_spec))
        key = jax.random.key(seed)
        gen = fn(params, toks_sharded, jnp.int32(S0), key)
        return jnp.concatenate([jnp.asarray(prompts), jnp.asarray(gen)],
                               axis=1)
