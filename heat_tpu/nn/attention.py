"""Sequence/context-parallel attention over the mesh.

The reference contains no attention code; what it has are the three
communication primitives long-sequence parallelism is built from
(SURVEY.md §5): the halo exchange (``heat/core/dndarray.py:360-433``), the
systolic ring of ``cdist`` (``heat/spatial/distance.py:280-362``), and the
axis-swap all-to-all (``heat/core/communication.py:1199-1341``). This module
completes them into the two standard long-context attention schemes, TPU
native:

* :func:`ring_attention` — blockwise attention with online (flash-style)
  softmax statistics; K/V blocks circulate the ring via ``ppermute`` while
  each device keeps its Q shard. Communication overlaps with the tile GEMMs.
  O(seq/devices) memory per device; exact (not approximate).
* :func:`ulysses_attention` — the all-to-all scheme: swap the sharded axis
  from sequence to heads (``lax.all_to_all``), run dense local attention per
  head group, swap back. Cheaper for many-head models when seq/heads ratios
  allow.
"""

from __future__ import annotations

import math
from functools import partial
from typing import Optional

import jax
import jax.numpy as jnp
from jax import shard_map

from ..core.communication import TPUCommunication, sanitize_comm
from ..core.dndarray import DNDarray
from ..core.pallas_kernels import flash_attention, pallas_enabled

__all__ = ["ring_attention", "ulysses_attention", "local_attention"]

_ATTN_CACHE: dict = {}


def local_attention(q, k, v, scale: Optional[float] = None, causal: bool = False):
    """Plain dense attention on local arrays (the single-device tile)."""
    if scale is None:
        scale = 1.0 / math.sqrt(q.shape[-1])
    if pallas_enabled() and q.ndim == 4:
        # blockwise online-softmax kernel (Pallas, VMEM tiles)
        return flash_attention(q, k, v, scale=float(scale), causal=causal)
    logits = jnp.einsum("...qd,...kd->...qk", q, k) * scale
    if causal:
        qn, kn = logits.shape[-2], logits.shape[-1]
        mask = jnp.tril(jnp.ones((qn, kn), bool), kn - qn)
        logits = jnp.where(mask, logits, -jnp.inf)
    weights = jax.nn.softmax(logits, axis=-1)
    return jnp.einsum("...qk,...kd->...qd", weights, v)


def _ring_body(q_blk, k_blk, v_blk, comm: TPUCommunication, scale: float):
    """Per-device ring attention with online softmax accumulation.

    q_blk: (B, Sq_local, H, D); k/v blk circulate. Accumulates
    (numerator, denominator, running max) so the result is exactly softmax
    over the full global key axis.
    """
    size = comm.size
    axis = comm.axis_name
    perm = [(j, (j + 1) % size) for j in range(size)]

    B, Sq, H, D = q_blk.shape
    q_heads = jnp.moveaxis(q_blk, 2, 1)  # (B, H, Sq, D)

    if pallas_enabled():
        # per-step flash kernel on the resident K/V block; fold (out, lse)
        acc = jnp.zeros((B, H, Sq, D), jnp.float32)
        lse = jnp.full((B, H, Sq), -jnp.inf, jnp.float32)
        k_cur, v_cur = k_blk, v_blk
        for step in range(size):
            k_heads = jnp.moveaxis(k_cur, 2, 1)
            v_heads = jnp.moveaxis(v_cur, 2, 1)
            out_i, lse_i = flash_attention(
                q_heads, k_heads, v_heads, scale=float(scale), return_lse=True
            )
            lse_new = jnp.logaddexp(lse, lse_i)
            acc = (
                acc * jnp.exp(lse - lse_new)[..., None]
                + out_i.astype(jnp.float32) * jnp.exp(lse_i - lse_new)[..., None]
            )
            lse = lse_new
            if step != size - 1:
                k_cur = jax.lax.ppermute(k_cur, axis, perm)
                v_cur = jax.lax.ppermute(v_cur, axis, perm)
        return jnp.moveaxis(acc, 1, 2).astype(q_blk.dtype)

    acc = jnp.zeros((B, H, Sq, D), jnp.float32)
    denom = jnp.zeros((B, H, Sq), jnp.float32)
    run_max = jnp.full((B, H, Sq), -jnp.inf, jnp.float32)

    k_cur, v_cur = k_blk, v_blk
    for step in range(size):
        k_heads = jnp.moveaxis(k_cur, 2, 1)
        v_heads = jnp.moveaxis(v_cur, 2, 1)
        logits = (
            jnp.einsum("bhqd,bhkd->bhqk", q_heads.astype(jnp.float32), k_heads.astype(jnp.float32))
            * scale
        )
        blk_max = jnp.max(logits, axis=-1)
        new_max = jnp.maximum(run_max, blk_max)
        correction = jnp.exp(run_max - new_max)
        p = jnp.exp(logits - new_max[..., None])
        acc = acc * correction[..., None] + jnp.einsum(
            "bhqk,bhkd->bhqd", p, v_heads.astype(jnp.float32)
        )
        denom = denom * correction + jnp.sum(p, axis=-1)
        run_max = new_max
        if step != size - 1:
            k_cur = jax.lax.ppermute(k_cur, axis, perm)
            v_cur = jax.lax.ppermute(v_cur, axis, perm)

    out = acc / jnp.maximum(denom[..., None], 1e-30)
    return jnp.moveaxis(out, 1, 2).astype(q_blk.dtype)  # (B, Sq, H, D)


def ring_attention(q, k, v, comm=None, scale: Optional[float] = None):
    """Exact attention over a sequence sharded across the mesh.

    Inputs: ``(batch, seq, heads, head_dim)`` jax arrays (or DNDarrays split
    along the sequence axis, axis 1). The K/V blocks circulate the ring —
    the reference's cdist systolic skeleton (``distance.py:280-362``) with
    flash-attention accumulation in place of the distance tile.
    """
    wrapped = isinstance(q, DNDarray)
    if wrapped:
        comm = q.comm
        if q.split != 1:
            raise ValueError("ring_attention expects sequence-split (split=1) inputs")
        qa, ka, va = q.larray, k.larray, v.larray
    else:
        comm = sanitize_comm(comm)
        qa, ka, va = q, k, v
    if scale is None:
        scale = 1.0 / math.sqrt(qa.shape[-1])

    key = (
        "ring_attn", qa.shape, ka.shape, str(qa.dtype), float(scale), comm.cache_key,
        pallas_enabled(),
    )
    fn = _ATTN_CACHE.get(key)
    if fn is None:
        spec = comm.spec(4, 1)  # (batch, seq✂, heads, dim)
        body = partial(_ring_body, comm=comm, scale=scale)
        sm = shard_map(
            body, mesh=comm.mesh, in_specs=(spec, spec, spec), out_specs=spec, check_vma=False
        )
        fn = jax.jit(sm)
        _ATTN_CACHE[key] = fn
    out = fn(qa, ka, va)
    if wrapped:
        return DNDarray(out, q.gshape, q.dtype, 1, q.device, comm)
    return out


def ulysses_attention(q, k, v, comm=None, scale: Optional[float] = None):
    """All-to-all (DeepSpeed-Ulysses style) sequence parallelism.

    Sequence-sharded ``(B, S✂, H, D)`` → all_to_all → head-sharded
    ``(B, S, H/size✂, D)`` → dense local attention → all_to_all back. The
    axis swap is the reference's ``Alltoallw`` resplit primitive
    (``communication.py:1199-1341``) as one XLA collective. Requires
    ``heads % mesh_size == 0``.
    """
    wrapped = isinstance(q, DNDarray)
    if wrapped:
        comm = q.comm
        if q.split != 1:
            raise ValueError("ulysses_attention expects sequence-split (split=1) inputs")
        qa, ka, va = q.larray, k.larray, v.larray
    else:
        comm = sanitize_comm(comm)
        qa, ka, va = q, k, v
    size = comm.size
    H = qa.shape[2]
    if H % size != 0:
        raise ValueError(f"heads ({H}) must be divisible by mesh size ({size})")
    if scale is None:
        scale = 1.0 / math.sqrt(qa.shape[-1])

    key = ("ulysses", qa.shape, str(qa.dtype), float(scale), comm.cache_key, pallas_enabled())
    fn = _ATTN_CACHE.get(key)
    if fn is None:
        spec = comm.spec(4, 1)
        axis = comm.axis_name

        def body(qb, kb, vb):
            # (B, s, H, D) local → heads sharded: (B, S, H/size, D)
            def seq2head(x):
                return jax.lax.all_to_all(x, axis, split_axis=2, concat_axis=1, tiled=True)

            def head2seq(x):
                return jax.lax.all_to_all(x, axis, split_axis=1, concat_axis=2, tiled=True)

            qh, kh, vh = seq2head(qb), seq2head(kb), seq2head(vb)
            out = local_attention(
                jnp.moveaxis(qh, 2, 1), jnp.moveaxis(kh, 2, 1), jnp.moveaxis(vh, 2, 1), scale
            )
            out = jnp.moveaxis(out, 1, 2)  # back to (B, S, h, D)
            return head2seq(out)

        sm = shard_map(
            body, mesh=comm.mesh, in_specs=(spec, spec, spec), out_specs=spec, check_vma=False
        )
        fn = jax.jit(sm)
        _ATTN_CACHE[key] = fn
    out = fn(qa, ka, va)
    if wrapped:
        return DNDarray(out, q.gshape, q.dtype, 1, q.device, comm)
    return out
