"""Sequence/context-parallel attention over the mesh.

The reference contains no attention code; what it has are the three
communication primitives long-sequence parallelism is built from
(SURVEY.md §5): the halo exchange (``heat/core/dndarray.py:360-433``), the
systolic ring of ``cdist`` (``heat/spatial/distance.py:280-362``), and the
axis-swap all-to-all (``heat/core/communication.py:1199-1341``). This module
completes them into the two standard long-context attention schemes, TPU
native:

* :func:`ring_attention` — blockwise attention with online (flash-style)
  softmax statistics; K/V blocks circulate the ring via ``ppermute`` while
  each device keeps its Q shard. Communication overlaps with the tile GEMMs.
  O(seq/devices) memory per device; exact (not approximate).
* :func:`ulysses_attention` — the all-to-all scheme: swap the sharded axis
  from sequence to heads (``lax.all_to_all``), run dense local attention per
  head group, swap back. Cheaper for many-head models when seq/heads ratios
  allow.
"""

from __future__ import annotations

import math
from functools import partial
from typing import Optional

import jax
import jax.numpy as jnp
from ..core._compat import shard_map

from ..core.communication import TPUCommunication, sanitize_comm
from ..core.dndarray import DNDarray
from ..core.pallas_kernels import flash_attention, interpret_vma_hazard, pallas_enabled

__all__ = ["ring_attention", "ulysses_attention", "local_attention"]

_ATTN_CACHE: dict = {}


def local_attention(q, k, v, scale: Optional[float] = None, causal: bool = False):
    """Plain dense attention on local arrays (the single-device tile)."""
    if scale is None:
        scale = 1.0 / math.sqrt(q.shape[-1])
    if pallas_enabled() and q.ndim == 4 and not interpret_vma_hazard(q, k, v):
        # blockwise online-softmax kernel (Pallas, VMEM tiles)
        return flash_attention(q, k, v, scale=float(scale), causal=causal)
    logits = jnp.einsum("...qd,...kd->...qk", q, k) * scale
    if causal:
        qn, kn = logits.shape[-2], logits.shape[-1]
        mask = jnp.tril(jnp.ones((qn, kn), bool), kn - qn)
        logits = jnp.where(mask, logits, -jnp.inf)
    weights = jax.nn.softmax(logits, axis=-1)
    return jnp.einsum("...qk,...kd->...qd", weights, v)


def _ring_body(q_blk, k_blk, v_blk, comm: TPUCommunication, scale: float, causal: bool = False):
    """Per-device ring attention with online softmax accumulation.

    q_blk: (B, Sq_local, H, D); k/v blk circulate. Accumulates
    (numerator, denominator, running max) so the result is exactly softmax
    over the full global key axis. With ``causal=True`` each step applies the
    global-position mask: the K/V block resident at step t originated at rank
    ``(rank - t) mod size``, so key j of that block has global index
    ``src*Sk + j``; it is visible to query i iff global_k <= global_q. Step 0
    holds the device's own diagonal block, so every query row sees at least
    itself and the running max stays finite.
    """
    size = comm.size
    axis = comm.axis_name
    perm = [(j, (j + 1) % size) for j in range(size)]

    B, Sq, H, D = q_blk.shape
    q_heads = jnp.moveaxis(q_blk, 2, 1)  # (B, H, Sq, D)

    if pallas_enabled() and not interpret_vma_hazard(q_blk, k_blk, v_blk):
        # per-step flash kernel on the resident K/V block; fold (out, lse).
        # Causal case: blocks are classified per step — step 0 holds the
        # device's own diagonal block (causal flash); any later block is
        # either fully visible (src rank < mine: plain flash) or fully
        # masked (src rank > mine: fold weight zeroed via lse=-inf) — the
        # kernel never materializes per-step logits either way.
        rank = jax.lax.axis_index(axis)
        acc = jnp.zeros((B, H, Sq, D), jnp.float32)
        lse = jnp.full((B, H, Sq), -jnp.inf, jnp.float32)
        k_cur, v_cur = k_blk, v_blk
        for step in range(size):
            k_heads = jnp.moveaxis(k_cur, 2, 1)
            v_heads = jnp.moveaxis(v_cur, 2, 1)
            out_i, lse_i = flash_attention(
                q_heads, k_heads, v_heads, scale=float(scale),
                causal=causal and step == 0, return_lse=True,
            )
            if causal and step > 0:
                visible = ((rank - step) % size) < rank
                lse_i = jnp.where(visible, lse_i, -jnp.inf)
            acc, lse = _fold(acc, lse, out_i.astype(jnp.float32), lse_i)
            if step != size - 1:
                k_cur = jax.lax.ppermute(k_cur, axis, perm)
                v_cur = jax.lax.ppermute(v_cur, axis, perm)
        return jnp.moveaxis(acc, 1, 2).astype(q_blk.dtype)

    rank = jax.lax.axis_index(axis)
    acc = jnp.zeros((B, H, Sq, D), jnp.float32)
    denom = jnp.zeros((B, H, Sq), jnp.float32)
    run_max = jnp.full((B, H, Sq), -jnp.inf, jnp.float32)

    k_cur, v_cur = k_blk, v_blk
    for step in range(size):
        k_heads = jnp.moveaxis(k_cur, 2, 1)
        v_heads = jnp.moveaxis(v_cur, 2, 1)
        logits = (
            jnp.einsum("bhqd,bhkd->bhqk", q_heads.astype(jnp.float32), k_heads.astype(jnp.float32))
            * scale
        )
        if causal:
            Sk = k_cur.shape[1]
            src = (rank - step) % size
            gq = rank * Sq + jnp.arange(Sq)[:, None]
            gk = src * Sk + jnp.arange(Sk)[None, :]
            logits = jnp.where(gk <= gq, logits, -jnp.inf)
        blk_max = jnp.max(logits, axis=-1)
        new_max = jnp.maximum(run_max, blk_max)
        # fully-masked blocks leave the running max untouched (avoids -inf-inf)
        new_max = jnp.where(jnp.isfinite(new_max), new_max, run_max)
        correction = jnp.where(jnp.isfinite(run_max), jnp.exp(run_max - new_max), 0.0)
        p = jnp.exp(logits - new_max[..., None])
        p = jnp.where(jnp.isfinite(logits), p, 0.0)
        acc = acc * correction[..., None] + jnp.einsum(
            "bhqk,bhkd->bhqd", p, v_heads.astype(jnp.float32)
        )
        denom = denom * correction + jnp.sum(p, axis=-1)
        run_max = new_max
        if step != size - 1:
            k_cur = jax.lax.ppermute(k_cur, axis, perm)
            v_cur = jax.lax.ppermute(v_cur, axis, perm)

    out = acc / jnp.maximum(denom[..., None], 1e-30)
    return jnp.moveaxis(out, 1, 2).astype(q_blk.dtype)  # (B, Sq, H, D)


def _block_attn(q, k, v, scale: float, causal: bool):
    """One attention block returning ``(out, lse)`` in f32 — the mergeable
    form every ring schedule folds. ``q``/``k``/``v``: ``(B, H, s, D)``.
    Routes through the flash kernel when enabled, else a dense jnp block."""
    if pallas_enabled() and not interpret_vma_hazard(q, k, v):
        out, lse = flash_attention(q, k, v, scale=float(scale), causal=causal,
                                   return_lse=True)
        return out.astype(jnp.float32), lse
    logits = jnp.einsum("bhqd,bhkd->bhqk", q.astype(jnp.float32),
                        k.astype(jnp.float32)) * scale
    if causal:
        sq, sk = logits.shape[-2], logits.shape[-1]
        row = jnp.arange(sq)[:, None]
        col = jnp.arange(sk)[None, :]
        logits = jnp.where(col <= row + (sk - sq), logits, -jnp.inf)
    m = jnp.max(logits, axis=-1)
    p = jnp.where(jnp.isfinite(logits), jnp.exp(logits - m[..., None]), 0.0)
    l = jnp.sum(p, axis=-1)
    out = jnp.einsum("bhqk,bhkd->bhqd", p, v.astype(jnp.float32))
    out = out / jnp.maximum(l[..., None], 1e-30)
    return out, m + jnp.log(jnp.maximum(l, 1e-30))


def _fold(acc, lse, out_i, lse_i):
    """Numerically-stable merge of two normalized attention pieces by their
    log-sum-exp weights; a ``lse_i = -inf`` piece is a no-op."""
    lse_new = jnp.logaddexp(lse, lse_i)
    w_old = jnp.where(jnp.isfinite(lse), jnp.exp(lse - lse_new), 0.0)
    w_new = jnp.where(jnp.isfinite(lse_i), jnp.exp(lse_i - lse_new), 0.0)
    return acc * w_old[..., None] + out_i * w_new[..., None], lse_new


def _ring_body_zigzag(q_blk, k_blk, v_blk, comm: TPUCommunication, scale: float):
    """Load-balanced causal ring attention (zigzag layout).

    The naive causal ring computes every ``(2c × 2c)`` block then masks it:
    device 0's queries see almost nothing (its steps fold to zero) while the
    last device needs every step — and since the ring synchronizes at each
    ``ppermute``, wall-clock is the BUSIEST device: 4c² of block work per
    step everywhere. Re-laying the sequence so device ``i`` holds chunks
    ``i`` and ``2n-1-i`` (half from the start, half from the end) makes the
    live work identical on every device: per step one always-visible
    half-block (late queries × early keys) plus exactly one of
    {early × early, late × late} — 2c² per step, half the naive cost, with
    zero load imbalance. The layout change is two ``ppermute`` streams in,
    two out; visibility per step is the chunk-order predicate ``j < r``.

    Local inputs ``(B, 2c, H, D)`` in contiguous split order; output in the
    same order (the zigzag layout is internal).
    """
    parts = (zigzag_layout(q_blk, comm), zigzag_layout(k_blk, comm),
             zigzag_layout(v_blk, comm))
    if parts[0] is None:  # single device: plain causal attention
        out = local_attention(jnp.moveaxis(q_blk, 2, 1), jnp.moveaxis(k_blk, 2, 1),
                              jnp.moveaxis(v_blk, 2, 1), scale, causal=True)
        return jnp.moveaxis(out, 1, 2)
    out = _zigzag_core(*parts, comm=comm, scale=scale)
    return zigzag_unlayout(out, comm)


def zigzag_layout(x, comm: TPUCommunication):
    """Contiguous split layout → zigzag layout along seq axis 1.

    Contiguous device ``s`` holds chunks ``(2s, 2s+1)``; zigzag device ``d``
    holds ``(d, 2n-1-d)``. Two permutation streams; at an even destination
    the A-stream carries the early chunk, at an odd one the B-stream does.
    Positionwise layers are layout-agnostic, so a transformer can relayout
    ONCE after embedding, run every attention layer in zigzag layout via
    :func:`_zigzag_core`, and invert once before the loss. Returns ``None``
    on a single-device comm (no layout needed)."""
    n = comm.size
    if n == 1:
        return None
    axis = comm.axis_name
    S2 = x.shape[1]
    if S2 % 2 != 0:
        raise ValueError(
            f"zigzag schedule needs the global sequence divisible by 2*size "
            f"(local block {S2} is odd)")
    c = S2 // 2
    even = (jax.lax.axis_index(axis) % 2) == 0
    a, b = x[:, :c], x[:, c:]
    p_a = [(s, 2 * s if 2 * s < n else 2 * n - 1 - 2 * s) for s in range(n)]
    p_b = [(s, 2 * s + 1 if 2 * s + 1 < n else 2 * n - 2 - 2 * s)
           for s in range(n)]
    ra = jax.lax.ppermute(a, axis, p_a)
    rb = jax.lax.ppermute(b, axis, p_b)
    early = jnp.where(even, ra, rb)
    late = jnp.where(even, rb, ra)
    return jnp.concatenate([early, late], axis=1)


def zigzag_unlayout(x, comm: TPUCommunication):
    """Inverse of :func:`zigzag_layout`: zigzag device ``d`` returns its
    early chunk ``d`` and late chunk ``2n-1-d`` to their contiguous owners
    (chunk ``h`` lives on device ``h//2``, slot ``h%2``)."""
    n = comm.size
    if n == 1:
        return x
    axis = comm.axis_name
    c = x.shape[1] // 2
    even = (jax.lax.axis_index(axis) % 2) == 0
    early, late = x[:, :c], x[:, c:]
    to0 = jnp.where(even, early, late)   # even-numbered chunks
    to1 = jnp.where(even, late, early)   # odd-numbered chunks
    p0 = [(d, d // 2 if d % 2 == 0 else (2 * n - 1 - d) // 2)
          for d in range(n)]
    p1 = [(d, (2 * n - 1 - d) // 2 if d % 2 == 0 else d // 2)
          for d in range(n)]
    r0 = jax.lax.ppermute(to0, axis, p0)
    r1 = jax.lax.ppermute(to1, axis, p1)
    return jnp.concatenate([r0, r1], axis=1)


def _zigzag_core(q_blk, k_blk, v_blk, comm: TPUCommunication, scale: float):
    """The balanced causal ring on ALREADY-zigzag-layouted ``(B, 2c, H, D)``
    blocks; output stays in zigzag layout."""
    n = comm.size
    axis = comm.axis_name
    B, S2, H, D = q_blk.shape
    c = S2 // 2
    r = jax.lax.axis_index(axis)

    qz = jnp.moveaxis(q_blk, 2, 1)                   # (B, H, 2c, D)
    kz, vz = k_blk, v_blk                            # (B, 2c, H, D)
    q_e, q_l = qz[:, :, :c], qz[:, :, c:]

    acc_e = jnp.zeros((B, H, c, D), jnp.float32)
    lse_e = jnp.full((B, H, c), -jnp.inf, jnp.float32)
    acc_l = jnp.zeros((B, H, c, D), jnp.float32)
    lse_l = jnp.full((B, H, c), -jnp.inf, jnp.float32)

    perm = [(i, (i + 1) % n) for i in range(n)]
    k_cur, v_cur = kz, vz
    for t in range(n):
        kh = jnp.moveaxis(k_cur, 2, 1)
        vh = jnp.moveaxis(v_cur, 2, 1)
        k_e, k_l = kh[:, :, :c], kh[:, :, c:]
        v_e, v_l = vh[:, :, :c], vh[:, :, c:]

        # late queries (chunks >= n) always see early keys (chunks < n)
        o, l = _block_attn(q_l, k_e, v_e, scale, causal=False)
        acc_l, lse_l = _fold(acc_l, lse_l, o, l)

        if t == 0:
            # resident diagonal blocks
            o, l = _block_attn(q_e, k_e, v_e, scale, causal=True)
            acc_e, lse_e = _fold(acc_e, lse_e, o, l)
            o, l = _block_attn(q_l, k_l, v_l, scale, causal=True)
            acc_l, lse_l = _fold(acc_l, lse_l, o, l)
        else:
            j = (r - t) % n  # origin rank of the resident K/V pair

            def early_live(_):
                o, l = _block_attn(q_e, k_e, v_e, scale, causal=False)
                dead = (jnp.zeros_like(acc_l),
                        jnp.full_like(lse_l, -jnp.inf))
                return (o, l), dead

            def late_live(_):
                o, l = _block_attn(q_l, k_l, v_l, scale, causal=False)
                dead = (jnp.zeros_like(acc_e),
                        jnp.full_like(lse_e, -jnp.inf))
                return dead, (o, l)

            # exactly ONE of {early x early, late x late} is causally live
            # per device per step — branch instead of compute-and-mask
            (oe, le), (ol, ll) = jax.lax.cond(j < r, early_live, late_live, None)
            acc_e, lse_e = _fold(acc_e, lse_e, oe, le)
            acc_l, lse_l = _fold(acc_l, lse_l, ol, ll)

        if t != n - 1:
            k_cur = jax.lax.ppermute(k_cur, axis, perm)
            v_cur = jax.lax.ppermute(v_cur, axis, perm)

    out = jnp.concatenate([acc_e, acc_l], axis=2)     # (B, H, 2c, D)
    return jnp.moveaxis(out, 1, 2).astype(q_blk.dtype)


def _ulysses_core(qb, kb, vb, comm: TPUCommunication, scale: float,
                  causal: bool):
    """DeepSpeed-Ulysses attention on local ``(B, s, H, D)`` blocks inside
    an enclosing shard_map: seq-sharded → all_to_all → head-sharded full
    sequence → dense local attention → all_to_all back. The comm size must
    divide the local head count (each device takes heads/size heads)."""
    axis = comm.axis_name

    def seq2head(x):
        return jax.lax.all_to_all(x, axis, split_axis=2, concat_axis=1, tiled=True)

    def head2seq(x):
        return jax.lax.all_to_all(x, axis, split_axis=1, concat_axis=2, tiled=True)

    qh, kh, vh = seq2head(qb), seq2head(kb), seq2head(vb)
    # after the swap every device holds the FULL sequence for its head
    # subset, so the ordinary causal mask applies locally
    out = local_attention(
        jnp.moveaxis(qh, 2, 1), jnp.moveaxis(kh, 2, 1), jnp.moveaxis(vh, 2, 1),
        scale, causal=causal,
    )
    return head2seq(jnp.moveaxis(out, 1, 2))  # back to (B, s, H, D)


def _attn_spec(comm, batch_axis):
    """(batch, seq✂, heads, dim) PartitionSpec; with ``batch_axis`` the
    batch dimension is sharded over that grid axis too."""
    if batch_axis is None:
        return comm.spec(4, 1)
    from jax.sharding import PartitionSpec

    return PartitionSpec(batch_axis, comm.axis_name, None, None)


def ring_attention(
    q, k, v, comm=None, scale: Optional[float] = None, causal: bool = False,
    batch_axis: Optional[str] = None, schedule: str = "ring",
):
    """Exact attention over a sequence sharded across the mesh.

    Inputs: ``(batch, seq, heads, head_dim)`` jax arrays (or DNDarrays split
    along the sequence axis, axis 1). The K/V blocks circulate the ring —
    the reference's cdist systolic skeleton (``distance.py:280-362``) with
    flash-attention accumulation in place of the distance tile. With
    ``causal=True`` the global causal mask is applied per ring step (for
    autoregressive/LM training on sequence-sharded inputs).

    ``schedule="zigzag"`` (causal only) uses the load-balanced layout —
    device ``i`` holds sequence chunks ``i`` and ``2n-1-i`` internally — so
    every device does identical live work per ring step (2 half-blocks vs
    the naive schedule's 4, where the masked-out blocks are computed then
    discarded and the last device gates the ring): ~2x causal wall-clock at
    scale. Exact same math; requires the global sequence divisible by
    ``2 * size``.

    On a :class:`~heat_tpu.core.communication.MeshGrid` axis view,
    ``batch_axis`` names another grid axis the batch dimension is sharded
    over — combined dp×sp: independent rings run per batch shard
    (``ring_attention(q, k, v, comm=grid.axis("sp"), batch_axis="dp")``).
    """
    if schedule not in ("ring", "zigzag"):
        raise ValueError(f"schedule must be 'ring' or 'zigzag', got {schedule!r}")
    if schedule == "zigzag" and not causal:
        raise ValueError(
            "schedule='zigzag' only applies to causal attention — the "
            "non-causal ring is already load-balanced")
    wrapped = isinstance(q, DNDarray)
    if wrapped:
        comm = q.comm
        if q.split != 1:
            raise ValueError("ring_attention expects sequence-split (split=1) inputs")
        qa, ka, va = q.larray, k.larray, v.larray
    else:
        comm = sanitize_comm(comm)
        qa, ka, va = q, k, v
    if scale is None:
        scale = 1.0 / math.sqrt(qa.shape[-1])

    key = (
        "ring_attn", qa.shape, ka.shape, str(qa.dtype), float(scale), comm.cache_key,
        pallas_enabled(), causal, batch_axis, schedule,
    )
    fn = _ATTN_CACHE.get(key)
    if fn is None:
        spec = _attn_spec(comm, batch_axis)
        if schedule == "zigzag":
            body = partial(_ring_body_zigzag, comm=comm, scale=scale)
        else:
            body = partial(_ring_body, comm=comm, scale=scale, causal=causal)
        sm = shard_map(
            body, mesh=comm.mesh, in_specs=(spec, spec, spec), out_specs=spec, check_vma=False
        )
        fn = jax.jit(sm)
        _ATTN_CACHE[key] = fn
    out = fn(qa, ka, va)
    if wrapped:
        return DNDarray(out, q.gshape, q.dtype, 1, q.device, comm)
    return out


def ulysses_attention(
    q, k, v, comm=None, scale: Optional[float] = None, causal: bool = False,
    batch_axis: Optional[str] = None,
):
    """All-to-all (DeepSpeed-Ulysses style) sequence parallelism.

    Sequence-sharded ``(B, S✂, H, D)`` → all_to_all → head-sharded
    ``(B, S, H/size✂, D)`` → dense local attention → all_to_all back. The
    axis swap is the reference's ``Alltoallw`` resplit primitive
    (``communication.py:1199-1341``) as one XLA collective. Requires
    ``heads % mesh_size == 0``.
    """
    wrapped = isinstance(q, DNDarray)
    if wrapped:
        comm = q.comm
        if q.split != 1:
            raise ValueError("ulysses_attention expects sequence-split (split=1) inputs")
        qa, ka, va = q.larray, k.larray, v.larray
    else:
        comm = sanitize_comm(comm)
        qa, ka, va = q, k, v
    size = comm.size
    H = qa.shape[2]
    if H % size != 0:
        raise ValueError(f"heads ({H}) must be divisible by mesh size ({size})")
    if scale is None:
        scale = 1.0 / math.sqrt(qa.shape[-1])

    key = (
        "ulysses", qa.shape, str(qa.dtype), float(scale), comm.cache_key,
        pallas_enabled(), causal, batch_axis,
    )
    fn = _ATTN_CACHE.get(key)
    if fn is None:
        spec = _attn_spec(comm, batch_axis)
        body = partial(_ulysses_core, comm=comm, scale=scale, causal=causal)
        sm = shard_map(
            body, mesh=comm.mesh, in_specs=(spec, spec, spec), out_specs=spec, check_vma=False
        )
        fn = jax.jit(sm)
        _ATTN_CACHE[key] = fn
    out = fn(qa, ka, va)
    if wrapped:
        return DNDarray(out, q.gshape, q.dtype, 1, q.device, comm)
    return out
