"""Sequence/context-parallel attention over the mesh.

The reference contains no attention code; what it has are the three
communication primitives long-sequence parallelism is built from
(SURVEY.md §5): the halo exchange (``heat/core/dndarray.py:360-433``), the
systolic ring of ``cdist`` (``heat/spatial/distance.py:280-362``), and the
axis-swap all-to-all (``heat/core/communication.py:1199-1341``). This module
completes them into the two standard long-context attention schemes, TPU
native:

* :func:`ring_attention` — blockwise attention with online (flash-style)
  softmax statistics; K/V blocks circulate the ring via ``ppermute`` while
  each device keeps its Q shard. Communication overlaps with the tile GEMMs.
  O(seq/devices) memory per device; exact (not approximate).
* :func:`ulysses_attention` — the all-to-all scheme: swap the sharded axis
  from sequence to heads (``lax.all_to_all``), run dense local attention per
  head group, swap back. Cheaper for many-head models when seq/heads ratios
  allow.
"""

from __future__ import annotations

import math
from functools import partial
from typing import Optional

import jax
import jax.numpy as jnp
from jax import shard_map

from ..core.communication import TPUCommunication, sanitize_comm
from ..core.dndarray import DNDarray
from ..core.pallas_kernels import flash_attention, interpret_vma_hazard, pallas_enabled

__all__ = ["ring_attention", "ulysses_attention", "local_attention"]

_ATTN_CACHE: dict = {}


def local_attention(q, k, v, scale: Optional[float] = None, causal: bool = False):
    """Plain dense attention on local arrays (the single-device tile)."""
    if scale is None:
        scale = 1.0 / math.sqrt(q.shape[-1])
    if pallas_enabled() and q.ndim == 4 and not interpret_vma_hazard(q, k, v):
        # blockwise online-softmax kernel (Pallas, VMEM tiles)
        return flash_attention(q, k, v, scale=float(scale), causal=causal)
    logits = jnp.einsum("...qd,...kd->...qk", q, k) * scale
    if causal:
        qn, kn = logits.shape[-2], logits.shape[-1]
        mask = jnp.tril(jnp.ones((qn, kn), bool), kn - qn)
        logits = jnp.where(mask, logits, -jnp.inf)
    weights = jax.nn.softmax(logits, axis=-1)
    return jnp.einsum("...qk,...kd->...qd", weights, v)


def _ring_body(q_blk, k_blk, v_blk, comm: TPUCommunication, scale: float, causal: bool = False):
    """Per-device ring attention with online softmax accumulation.

    q_blk: (B, Sq_local, H, D); k/v blk circulate. Accumulates
    (numerator, denominator, running max) so the result is exactly softmax
    over the full global key axis. With ``causal=True`` each step applies the
    global-position mask: the K/V block resident at step t originated at rank
    ``(rank - t) mod size``, so key j of that block has global index
    ``src*Sk + j``; it is visible to query i iff global_k <= global_q. Step 0
    holds the device's own diagonal block, so every query row sees at least
    itself and the running max stays finite.
    """
    size = comm.size
    axis = comm.axis_name
    perm = [(j, (j + 1) % size) for j in range(size)]

    B, Sq, H, D = q_blk.shape
    q_heads = jnp.moveaxis(q_blk, 2, 1)  # (B, H, Sq, D)

    if pallas_enabled() and not interpret_vma_hazard(q_blk, k_blk, v_blk):
        # per-step flash kernel on the resident K/V block; fold (out, lse).
        # Causal case: blocks are classified per step — step 0 holds the
        # device's own diagonal block (causal flash); any later block is
        # either fully visible (src rank < mine: plain flash) or fully
        # masked (src rank > mine: fold weight zeroed via lse=-inf) — the
        # kernel never materializes per-step logits either way.
        rank = jax.lax.axis_index(axis)
        acc = jnp.zeros((B, H, Sq, D), jnp.float32)
        lse = jnp.full((B, H, Sq), -jnp.inf, jnp.float32)
        k_cur, v_cur = k_blk, v_blk
        for step in range(size):
            k_heads = jnp.moveaxis(k_cur, 2, 1)
            v_heads = jnp.moveaxis(v_cur, 2, 1)
            out_i, lse_i = flash_attention(
                q_heads, k_heads, v_heads, scale=float(scale),
                causal=causal and step == 0, return_lse=True,
            )
            if causal and step > 0:
                visible = ((rank - step) % size) < rank
                lse_i = jnp.where(visible, lse_i, -jnp.inf)
            lse_new = jnp.logaddexp(lse, lse_i)
            # guard the -inf−(-inf) corner (first fold of each row)
            w_old = jnp.where(jnp.isfinite(lse), jnp.exp(lse - lse_new), 0.0)
            w_new = jnp.where(jnp.isfinite(lse_i), jnp.exp(lse_i - lse_new), 0.0)
            acc = acc * w_old[..., None] + out_i.astype(jnp.float32) * w_new[..., None]
            lse = lse_new
            if step != size - 1:
                k_cur = jax.lax.ppermute(k_cur, axis, perm)
                v_cur = jax.lax.ppermute(v_cur, axis, perm)
        return jnp.moveaxis(acc, 1, 2).astype(q_blk.dtype)

    rank = jax.lax.axis_index(axis)
    acc = jnp.zeros((B, H, Sq, D), jnp.float32)
    denom = jnp.zeros((B, H, Sq), jnp.float32)
    run_max = jnp.full((B, H, Sq), -jnp.inf, jnp.float32)

    k_cur, v_cur = k_blk, v_blk
    for step in range(size):
        k_heads = jnp.moveaxis(k_cur, 2, 1)
        v_heads = jnp.moveaxis(v_cur, 2, 1)
        logits = (
            jnp.einsum("bhqd,bhkd->bhqk", q_heads.astype(jnp.float32), k_heads.astype(jnp.float32))
            * scale
        )
        if causal:
            Sk = k_cur.shape[1]
            src = (rank - step) % size
            gq = rank * Sq + jnp.arange(Sq)[:, None]
            gk = src * Sk + jnp.arange(Sk)[None, :]
            logits = jnp.where(gk <= gq, logits, -jnp.inf)
        blk_max = jnp.max(logits, axis=-1)
        new_max = jnp.maximum(run_max, blk_max)
        # fully-masked blocks leave the running max untouched (avoids -inf-inf)
        new_max = jnp.where(jnp.isfinite(new_max), new_max, run_max)
        correction = jnp.where(jnp.isfinite(run_max), jnp.exp(run_max - new_max), 0.0)
        p = jnp.exp(logits - new_max[..., None])
        p = jnp.where(jnp.isfinite(logits), p, 0.0)
        acc = acc * correction[..., None] + jnp.einsum(
            "bhqk,bhkd->bhqd", p, v_heads.astype(jnp.float32)
        )
        denom = denom * correction + jnp.sum(p, axis=-1)
        run_max = new_max
        if step != size - 1:
            k_cur = jax.lax.ppermute(k_cur, axis, perm)
            v_cur = jax.lax.ppermute(v_cur, axis, perm)

    out = acc / jnp.maximum(denom[..., None], 1e-30)
    return jnp.moveaxis(out, 1, 2).astype(q_blk.dtype)  # (B, Sq, H, D)


def _attn_spec(comm, batch_axis):
    """(batch, seq✂, heads, dim) PartitionSpec; with ``batch_axis`` the
    batch dimension is sharded over that grid axis too."""
    if batch_axis is None:
        return comm.spec(4, 1)
    from jax.sharding import PartitionSpec

    return PartitionSpec(batch_axis, comm.axis_name, None, None)


def ring_attention(
    q, k, v, comm=None, scale: Optional[float] = None, causal: bool = False,
    batch_axis: Optional[str] = None,
):
    """Exact attention over a sequence sharded across the mesh.

    Inputs: ``(batch, seq, heads, head_dim)`` jax arrays (or DNDarrays split
    along the sequence axis, axis 1). The K/V blocks circulate the ring —
    the reference's cdist systolic skeleton (``distance.py:280-362``) with
    flash-attention accumulation in place of the distance tile. With
    ``causal=True`` the global causal mask is applied per ring step (for
    autoregressive/LM training on sequence-sharded inputs).

    On a :class:`~heat_tpu.core.communication.MeshGrid` axis view,
    ``batch_axis`` names another grid axis the batch dimension is sharded
    over — combined dp×sp: independent rings run per batch shard
    (``ring_attention(q, k, v, comm=grid.axis("sp"), batch_axis="dp")``).
    """
    wrapped = isinstance(q, DNDarray)
    if wrapped:
        comm = q.comm
        if q.split != 1:
            raise ValueError("ring_attention expects sequence-split (split=1) inputs")
        qa, ka, va = q.larray, k.larray, v.larray
    else:
        comm = sanitize_comm(comm)
        qa, ka, va = q, k, v
    if scale is None:
        scale = 1.0 / math.sqrt(qa.shape[-1])

    key = (
        "ring_attn", qa.shape, ka.shape, str(qa.dtype), float(scale), comm.cache_key,
        pallas_enabled(), causal, batch_axis,
    )
    fn = _ATTN_CACHE.get(key)
    if fn is None:
        spec = _attn_spec(comm, batch_axis)
        body = partial(_ring_body, comm=comm, scale=scale, causal=causal)
        sm = shard_map(
            body, mesh=comm.mesh, in_specs=(spec, spec, spec), out_specs=spec, check_vma=False
        )
        fn = jax.jit(sm)
        _ATTN_CACHE[key] = fn
    out = fn(qa, ka, va)
    if wrapped:
        return DNDarray(out, q.gshape, q.dtype, 1, q.device, comm)
    return out


def ulysses_attention(
    q, k, v, comm=None, scale: Optional[float] = None, causal: bool = False,
    batch_axis: Optional[str] = None,
):
    """All-to-all (DeepSpeed-Ulysses style) sequence parallelism.

    Sequence-sharded ``(B, S✂, H, D)`` → all_to_all → head-sharded
    ``(B, S, H/size✂, D)`` → dense local attention → all_to_all back. The
    axis swap is the reference's ``Alltoallw`` resplit primitive
    (``communication.py:1199-1341``) as one XLA collective. Requires
    ``heads % mesh_size == 0``.
    """
    wrapped = isinstance(q, DNDarray)
    if wrapped:
        comm = q.comm
        if q.split != 1:
            raise ValueError("ulysses_attention expects sequence-split (split=1) inputs")
        qa, ka, va = q.larray, k.larray, v.larray
    else:
        comm = sanitize_comm(comm)
        qa, ka, va = q, k, v
    size = comm.size
    H = qa.shape[2]
    if H % size != 0:
        raise ValueError(f"heads ({H}) must be divisible by mesh size ({size})")
    if scale is None:
        scale = 1.0 / math.sqrt(qa.shape[-1])

    key = (
        "ulysses", qa.shape, str(qa.dtype), float(scale), comm.cache_key,
        pallas_enabled(), causal, batch_axis,
    )
    fn = _ATTN_CACHE.get(key)
    if fn is None:
        spec = _attn_spec(comm, batch_axis)
        axis = comm.axis_name

        def body(qb, kb, vb):
            # (B, s, H, D) local → heads sharded: (B, S, H/size, D)
            def seq2head(x):
                return jax.lax.all_to_all(x, axis, split_axis=2, concat_axis=1, tiled=True)

            def head2seq(x):
                return jax.lax.all_to_all(x, axis, split_axis=1, concat_axis=2, tiled=True)

            qh, kh, vh = seq2head(qb), seq2head(kb), seq2head(vb)
            # after the swap every device holds the FULL sequence for its
            # head subset, so the ordinary causal mask applies locally
            out = local_attention(
                jnp.moveaxis(qh, 2, 1), jnp.moveaxis(kh, 2, 1), jnp.moveaxis(vh, 2, 1),
                scale, causal=causal,
            )
            out = jnp.moveaxis(out, 1, 2)  # back to (B, S, h, D)
            return head2seq(out)

        sm = shard_map(
            body, mesh=comm.mesh, in_specs=(spec, spec, spec), out_specs=spec, check_vma=False
        )
        fn = jax.jit(sm)
        _ATTN_CACHE[key] = fn
    out = fn(qa, ka, va)
    if wrapped:
        return DNDarray(out, q.gshape, q.dtype, 1, q.device, comm)
    return out
