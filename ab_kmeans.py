import time, numpy as np, jax, jax.numpy as jnp
import heat_tpu as ht
from heat_tpu.cluster.kmeans import _lloyd_fori_fn
from heat_tpu.core import pallas_kernels as pk

n, d, k = 1 << 23, 64, 8
ht.random.seed(0)
x = ht.random.rand(n, d, dtype=ht.float32, split=0)
xp = x.larray
jdt = xp.dtype

def run(pallas, iters):
    pk.set_pallas(pallas)
    fn = _lloyd_fori_fn(xp.shape, jdt, k, n, x.comm)
    c0 = xp[:k]
    out = fn(xp, c0, 2); float(np.asarray(out[1]))
    t0 = time.perf_counter(); out = fn(xp, c0, 2); float(np.asarray(out[1])); t1 = time.perf_counter()
    out = fn(xp, c0, 2 + iters); float(np.asarray(out[1])); t2 = time.perf_counter()
    return iters / ((t2 - t1) - (t1 - t0))

for pallas in (False, True, False, True):
    print("pallas", pallas, "iter/s:", round(run(pallas, 50), 1), flush=True)

