import functools, sys, numpy as np, jax, jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu
import heat_tpu

def _i32(v): return jnp.asarray(v, jnp.int32)
n, d, kp, bm = 1 << 20, 64, 128, 1024
acc = jnp.float32
PREC = jax.lax.Precision.DEFAULT

def kern(x_ref, c_ref, m_ref, s_ref, a_s, *, sub):
    step = pl.program_id(0); nsteps = pl.num_programs(0)
    @pl.when(step == 0)
    def _():
        a_s[...] = jnp.zeros_like(a_s)
    x = x_ref[...].astype(acc); c = c_ref[...].astype(acc); valid = m_ref[...].astype(acc)
    c2 = jnp.sum(c*c, axis=1)[None, :]
    xc = jax.lax.dot_general(x, c, dimension_numbers=(((1,),(1,)),((),())), preferred_element_type=acc, precision=PREC)
    scores = c2 - 2.0*xc
    labels = jax.lax.argmin(scores, 1, jnp.int32)
    if sub == "argmin_only":
        a_s[...] += jnp.broadcast_to(labels.astype(acc).sum(), a_s.shape)
    elif sub == "onehot_sum":
        onehot = (labels[:, None] == jax.lax.broadcasted_iota(jnp.int32, (bm, kp), 1)).astype(acc) * valid
        a_s[...] += jnp.broadcast_to(jnp.sum(onehot), a_s.shape)
    elif sub == "dot_rev":
        onehot = (labels[:, None] == jax.lax.broadcasted_iota(jnp.int32, (bm, kp), 1)).astype(acc) * valid
        a_s[...] += jax.lax.dot_general(onehot, x, dimension_numbers=(((0,),(0,)),((),())), preferred_element_type=acc, precision=PREC)[:, :128][: a_s.shape[0]]
    elif sub == "dot_t":
        oh_t = (jax.lax.broadcasted_iota(jnp.int32, (kp, bm), 0) == labels[None, :]).astype(acc) * valid[None, :, 0] if False else (jax.lax.broadcasted_iota(jnp.int32, (kp, bm), 0) == jnp.broadcast_to(labels[None, :], (kp, bm))).astype(acc)
        a_s[...] += jax.lax.dot_general(oh_t, x, dimension_numbers=(((1,),(0,)),((),())), preferred_element_type=acc, precision=PREC)[: a_s.shape[0]]
    @pl.when(step == nsteps - 1)
    def _():
        s_ref[...] = a_s[...].astype(s_ref.dtype)

x = jnp.ones((n, d), jnp.float32); c = jnp.ones((kp, d), jnp.float32); m = jnp.ones((n, 1), jnp.float32)

for sub in ("argmin_only", "onehot_sum", "dot_t", "dot_rev"):
    try:
        out = pl.pallas_call(
            functools.partial(kern, sub=sub),
            grid=(n // bm,),
            in_specs=[pl.BlockSpec((bm, d), lambda i: (_i32(i), _i32(0))),
                      pl.BlockSpec((kp, d), lambda i: (_i32(0), _i32(0))),
                      pl.BlockSpec((bm, 1), lambda i: (_i32(i), _i32(0)))],
            out_specs=[pl.BlockSpec((kp, d), lambda i: (_i32(0), _i32(0)))],
            out_shape=[jax.ShapeDtypeStruct((kp, d), acc)],
            scratch_shapes=[pltpu.VMEM((kp, d), acc)],
        )(x, c, m)
        jax.block_until_ready(out)
        print(sub, "OK", flush=True)
    except Exception as e:
        print(sub, "FAIL:", str(e)[:150].replace("\n", " "), flush=True)
