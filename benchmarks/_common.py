"""Shared helpers for the benchmark drivers.

Import this BEFORE ``heat_tpu``:

    sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
    from _common import maybe_init_distributed
    maybe_init_distributed()        # must precede the heat_tpu import

    import heat_tpu as ht

``maybe_init_distributed`` must run before heat_tpu builds its default mesh
from ``jax.devices()`` — on a multi-host pod the mesh has to span every host.
"""

import sys


def maybe_init_distributed() -> None:
    """Call ``jax.distributed.initialize()`` when ``--distributed`` is given."""
    if "--distributed" in sys.argv:
        import jax

        jax.distributed.initialize()  # topology from the TPU pod environment


def add_common_args(parser) -> None:
    parser.add_argument(
        "--distributed",
        action="store_true",
        help="multi-host pod (jax.distributed.initialize() ran at import)",
    )
