"""Shared helpers for the benchmark drivers.

Importing this module puts the repo root on ``sys.path`` so the drivers can
``import heat_tpu`` when invoked as scripts. ``maybe_init_distributed()``
may be called before or after importing heat_tpu (the package import is
backend-free); it must only precede any array work:

    from _common import add_common_args, maybe_init_distributed
    maybe_init_distributed()
    import heat_tpu as ht

``maybe_init_distributed`` must run before heat_tpu builds its default mesh
from ``jax.devices()`` — on a multi-host pod the mesh has to span every host.
"""

import os
import sys

# the drivers are invoked as scripts (``python benchmarks/kmeans/...``), so
# the repo root is not on sys.path; add it so ``import heat_tpu`` resolves
_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
if _ROOT not in sys.path:
    sys.path.insert(0, _ROOT)


def maybe_init_distributed() -> None:
    """Join the pod when ``--distributed`` is given (heat_tpu's import is
    backend-free, so this works from any driver before array work)."""
    if "--distributed" in sys.argv:
        import heat_tpu as ht

        ht.distributed_init()  # topology from the TPU pod environment


def add_common_args(parser) -> None:
    parser.add_argument(
        "--distributed",
        action="store_true",
        help="multi-host pod (jax.distributed.initialize() ran at import)",
    )
