"""KMeans benchmark driver (reference ``benchmarks/kmeans/heat-cpu.py:20-26``:
10 trials of fit with k=8, 30 iterations, timed with perf_counter).

Synthetic data stands in for the cityscapes H5 when no file is given; pass
``--file`` / ``--dataset`` to reproduce the reference workload exactly.
"""

import argparse
import json
import time

import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
from _common import add_common_args, maybe_init_distributed

maybe_init_distributed()  # must precede the heat_tpu import (mesh creation)

import jax  # noqa: F401  (re-exported for drivers that sync on results)

import heat_tpu as ht


def main():
    p = argparse.ArgumentParser()
    add_common_args(p)
    p.add_argument("--n", type=int, default=1 << 20)
    p.add_argument("--d", type=int, default=64)
    p.add_argument("--k", type=int, default=8)
    p.add_argument("--iters", type=int, default=30)
    p.add_argument("--trials", type=int, default=10)
    p.add_argument("--file", type=str, default=None)
    p.add_argument("--dataset", type=str, default="data")
    args = p.parse_args()

    if args.file:
        data = ht.load(args.file, dataset=args.dataset, split=0)
    else:
        ht.random.seed(0)
        data = ht.random.rand(args.n, args.d, dtype=ht.float32, split=0)

    times = []
    for _ in range(args.trials):
        kmeans = ht.cluster.KMeans(n_clusters=args.k, init="kmeans++", max_iter=args.iters, tol=-1.0)
        t0 = time.perf_counter()
        kmeans.fit(data)
        t1 = time.perf_counter()
        times.append(t1 - t0)

    print(json.dumps({
        "benchmark": "kmeans",
        "n": data.shape[0], "d": data.shape[1], "k": args.k, "iters": args.iters,
        "trial_seconds": times,
        "mean_seconds": sum(times) / len(times),
        "iters_per_second": args.iters / (sum(times) / len(times)),
    }))


if __name__ == "__main__":
    main()
