"""KMeans benchmark driver (reference ``benchmarks/kmeans/heat-cpu.py:20-26``:
10 trials of fit with k=8, 30 iterations, timed with perf_counter).

Synthetic data stands in for the cityscapes H5 when no file is given; pass
``--file`` / ``--dataset`` to reproduce the reference workload exactly.
"""

import argparse
import json
import time

import sys

import jax

if "--distributed" in sys.argv:
    # must run before heat_tpu builds its default mesh from jax.devices()
    jax.distributed.initialize()  # topology from the TPU pod environment

import heat_tpu as ht


def main():
    p = argparse.ArgumentParser()
    p.add_argument("--distributed", action="store_true",
                   help="multi-host pod (jax.distributed.initialize() ran at import)")
    p.add_argument("--n", type=int, default=1 << 20)
    p.add_argument("--d", type=int, default=64)
    p.add_argument("--k", type=int, default=8)
    p.add_argument("--iters", type=int, default=30)
    p.add_argument("--trials", type=int, default=10)
    p.add_argument("--file", type=str, default=None)
    p.add_argument("--dataset", type=str, default="data")
    args = p.parse_args()

    if args.file:
        data = ht.load(args.file, dataset=args.dataset, split=0)
    else:
        ht.random.seed(0)
        data = ht.random.rand(args.n, args.d, dtype=ht.float32, split=0)

    times = []
    for _ in range(args.trials):
        kmeans = ht.cluster.KMeans(n_clusters=args.k, init="kmeans++", max_iter=args.iters, tol=-1.0)
        t0 = time.perf_counter()
        kmeans.fit(data)
        t1 = time.perf_counter()
        times.append(t1 - t0)

    print(json.dumps({
        "benchmark": "kmeans",
        "n": data.shape[0], "d": data.shape[1], "k": args.k, "iters": args.iters,
        "trial_seconds": times,
        "mean_seconds": sum(times) / len(times),
        "iters_per_second": args.iters / (sum(times) / len(times)),
    }))


if __name__ == "__main__":
    main()
