"""Distance-matrix benchmark driver (reference
``benchmarks/distance_matrix/heat-cpu.py:21-34``: cdist with
quadratic_expansion on/off over a split-0 array, SUSY H5 in the reference).

Reports wall time and effective GB/s of the output distance matrix — the
driver metric for the ring all-to-all workload.
"""

import argparse
import json
import time

import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
from _common import add_common_args, maybe_init_distributed

maybe_init_distributed()  # must precede the heat_tpu import (mesh creation)

import jax  # noqa: F401  (re-exported for drivers that sync on results)

import heat_tpu as ht


def main():
    p = argparse.ArgumentParser()
    add_common_args(p)
    p.add_argument("--n", type=int, default=40_000)
    p.add_argument("--d", type=int, default=18)  # SUSY has 18 features
    p.add_argument("--trials", type=int, default=3)
    p.add_argument("--quadratic-expansion", action=argparse.BooleanOptionalAction, default=True)
    p.add_argument("--file", type=str, default=None)
    p.add_argument("--dataset", type=str, default="data")
    args = p.parse_args()

    if args.file:
        data = ht.load(args.file, dataset=args.dataset, split=0)
    else:
        ht.random.seed(0)
        data = ht.random.rand(args.n, args.d, dtype=ht.float32, split=0)

    # warmup/compile
    d = ht.spatial.cdist(data, quadratic_expansion=args.quadratic_expansion)
    jax.block_until_ready(d.larray)

    times = []
    for _ in range(args.trials):
        t0 = time.perf_counter()
        d = ht.spatial.cdist(data, quadratic_expansion=args.quadratic_expansion)
        jax.block_until_ready(d.larray)
        times.append(time.perf_counter() - t0)

    n = data.shape[0]
    out_bytes = n * n * 4
    best = min(times)
    print(json.dumps({
        "benchmark": "distance_matrix",
        "n": n, "d": data.shape[1],
        "quadratic_expansion": args.quadratic_expansion,
        "trial_seconds": times,
        "best_seconds": best,
        "output_gb_per_second": out_bytes / best / 1e9,
    }))


if __name__ == "__main__":
    main()
