"""Statistical-moments benchmark driver (reference
``benchmarks/statistical_moments/heat-cpu.py:21-28``: mean and std over
axes None/0/1 of a split array)."""

import argparse
import json
import time

import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
from _common import add_common_args, maybe_init_distributed

maybe_init_distributed()  # must precede the heat_tpu import (mesh creation)

import jax  # noqa: F401  (re-exported for drivers that sync on results)

import heat_tpu as ht


def main():
    p = argparse.ArgumentParser()
    add_common_args(p)
    p.add_argument("--n", type=int, default=1 << 22)
    p.add_argument("--d", type=int, default=64)
    p.add_argument("--trials", type=int, default=5)
    p.add_argument("--file", type=str, default=None)
    p.add_argument("--dataset", type=str, default="data")
    args = p.parse_args()

    if args.file:
        data = ht.load(args.file, dataset=args.dataset, split=0)
    else:
        ht.random.seed(0)
        data = ht.random.rand(args.n, args.d, dtype=ht.float32, split=0)

    results = {}
    for axis in (None, 0, 1):
        for name, fn in (("mean", ht.mean), ("std", ht.std)):
            out = fn(data, axis)  # warmup
            jax.block_until_ready(out.larray)
            t0 = time.perf_counter()
            for _ in range(args.trials):
                out = fn(data, axis)
                jax.block_until_ready(out.larray)
            results[f"{name}_axis_{axis}"] = (time.perf_counter() - t0) / args.trials

    print(json.dumps({
        "benchmark": "statistical_moments",
        "n": data.shape[0], "d": data.shape[1],
        "seconds_per_op": results,
    }))


if __name__ == "__main__":
    main()
