"""Lasso benchmark driver (reference ``benchmarks/lasso/``: fit wall-time)."""

import argparse
import json
import time

import numpy as np

import sys

import jax

if "--distributed" in sys.argv:
    # must run before heat_tpu builds its default mesh from jax.devices()
    jax.distributed.initialize()  # topology from the TPU pod environment

import heat_tpu as ht


def main():
    p = argparse.ArgumentParser()
    p.add_argument("--distributed", action="store_true",
                   help="multi-host pod (jax.distributed.initialize() ran at import)")
    p.add_argument("--n", type=int, default=100_000)
    p.add_argument("--d", type=int, default=64)
    p.add_argument("--iters", type=int, default=20)
    p.add_argument("--trials", type=int, default=3)
    args = p.parse_args()

    rng = np.random.default_rng(0)
    X = rng.normal(size=(args.n, args.d)).astype(np.float32)
    w = np.zeros(args.d, np.float32)
    w[: args.d // 4] = rng.normal(size=args.d // 4)
    y = X @ w + 0.01 * rng.normal(size=args.n).astype(np.float32)

    xd = ht.array(X, split=0)
    yd = ht.array(y, split=0)

    times = []
    for _ in range(args.trials):
        lasso = ht.regression.Lasso(lam=0.01, max_iter=args.iters, tol=-1.0)
        t0 = time.perf_counter()
        lasso.fit(xd, yd)
        times.append(time.perf_counter() - t0)

    print(json.dumps({
        "benchmark": "lasso",
        "n": args.n, "d": args.d, "iters": args.iters,
        "trial_seconds": times,
        "mean_seconds": sum(times) / len(times),
    }))


if __name__ == "__main__":
    main()
