"""Lasso benchmark driver (reference ``benchmarks/lasso/``: fit wall-time)."""

import argparse
import json
import time

import numpy as np

import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
from _common import add_common_args, maybe_init_distributed

maybe_init_distributed()  # must precede the heat_tpu import (mesh creation)

import jax  # noqa: F401  (re-exported for drivers that sync on results)

import heat_tpu as ht


def main():
    p = argparse.ArgumentParser()
    add_common_args(p)
    p.add_argument("--n", type=int, default=100_000)
    p.add_argument("--d", type=int, default=64)
    p.add_argument("--iters", type=int, default=20)
    p.add_argument("--trials", type=int, default=3)
    args = p.parse_args()

    rng = np.random.default_rng(0)
    X = rng.normal(size=(args.n, args.d)).astype(np.float32)
    w = np.zeros(args.d, np.float32)
    w[: args.d // 4] = rng.normal(size=args.d // 4)
    y = X @ w + 0.01 * rng.normal(size=args.n).astype(np.float32)

    xd = ht.array(X, split=0)
    yd = ht.array(y, split=0)

    times = []
    for _ in range(args.trials):
        lasso = ht.regression.Lasso(lam=0.01, max_iter=args.iters, tol=-1.0)
        t0 = time.perf_counter()
        lasso.fit(xd, yd)
        times.append(time.perf_counter() - t0)

    print(json.dumps({
        "benchmark": "lasso",
        "n": args.n, "d": args.d, "iters": args.iters,
        "trial_seconds": times,
        "mean_seconds": sum(times) / len(times),
    }))


if __name__ == "__main__":
    main()
