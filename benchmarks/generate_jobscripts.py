#!/usr/bin/env python
"""Generate TPU launch scripts for the benchmark suite.

TPU-native analog of the reference's SLURM jobscript generator
(``benchmarks/generate_jobscripts.py:11-61``): instead of ``srun`` over MPI
ranks, it emits

* **single-host** scripts (one process drives all local chips through the
  device mesh — the v5e-1/-4/-8 cases), and
* **multi-host pod** scripts (``gcloud compute tpus tpu-vm ssh --worker=all``
  running the same SPMD program on every host; ``jax.distributed.initialize``
  picks up the pod topology from the TPU environment — the v5e-16+ cases),

for every (benchmark × topology × strong/weak) combination in
``benchmarks/config.json``. Weak scaling sizes are ``weak_per_chip × chips``,
or ``weak_per_chip × sqrt(chips)`` for workloads marked
``"weak_scaling": "sqrt"`` (quadratic-memory outputs like distance_matrix).

Usage::

    python benchmarks/generate_jobscripts.py --out jobscripts \
        [--tpu-name NAME --zone ZONE --project PROJECT] [--benchmark kmeans]
"""

import argparse
import json
import os
import stat

SINGLE_HOST_TEMPLATE = """#!/bin/bash -x
# {name}: single-host TPU ({topology}, {chips} chip(s))
OUT="$(cd "$(dirname "$0")" && pwd)/{output}"
cd "$(dirname "$0")/{bench_rel}"

python -u {script} {parameters} 2>&1 | tee "$OUT"
"""

MULTI_HOST_TEMPLATE = """#!/bin/bash -x
# {name}: multi-host TPU pod ({topology}, {chips} chips)
# Requires: gcloud auth + a provisioned TPU pod slice; the repo present at
# the same path on every worker (use `gcloud ... scp --recurse` or NFS).
TPU_NAME=${{TPU_NAME:-{tpu_name}}}
ZONE=${{ZONE:-{zone}}}
PROJECT=${{PROJECT:-{project}}}

gcloud compute tpus tpu-vm ssh "$TPU_NAME" \\
  --zone "$ZONE" --project "$PROJECT" --worker=all \\
  --command "cd {remote_dir} && python -u {script} --distributed {parameters}" \\
  2>&1 | tee {output}
"""

# chips per topology label
def chips_of(topology: str) -> int:
    return int(topology.rsplit("-", 1)[1])


def parameters_for(bench: str, cfg: dict, n: int):
    """Yield ``(variant_suffix, cli_parameters)`` for every sweep variant."""
    if bench == "kmeans":
        yield "", (
            f"--n {n} --d {cfg['features']} --k {cfg['clusters']} "
            f"--iters {cfg['iterations']} --trials {cfg['trials']}"
        )
    elif bench == "distance_matrix":
        for quad in cfg.get("quadratic_expansion", [True]):
            flag = "--quadratic-expansion" if quad else "--no-quadratic-expansion"
            yield ("-quad" if quad else "-noquad"), f"--n {n} --d {cfg['features']} {flag}"
    elif bench == "statistical_moments":
        # the driver itself sweeps axes None/0/1 in one run
        yield "", f"--n {n} --d {cfg['features']}"
    elif bench == "lasso":
        yield "", f"--n {n} --iters {cfg['iterations']}"
    else:
        raise ValueError(f"unknown benchmark {bench}")


def main() -> None:
    p = argparse.ArgumentParser()
    p.add_argument("--config", default=os.path.join(os.path.dirname(__file__), "config.json"))
    p.add_argument("--out", default="jobscripts")
    p.add_argument("--benchmark", default=None, help="only this benchmark")
    p.add_argument("--tpu-name", default="heat-tpu-pod")
    p.add_argument("--zone", default="us-central1-a")
    p.add_argument("--project", default="my-project")
    p.add_argument("--remote-dir", default="~/heat_tpu/benchmarks")
    args = p.parse_args()

    with open(args.config) as f:
        config = json.load(f)

    os.makedirs(args.out, exist_ok=True)
    # single-host scripts cd from the output dir to the benchmarks dir
    bench_dir = os.path.dirname(os.path.abspath(args.config))
    bench_rel = os.path.relpath(bench_dir, os.path.abspath(args.out))
    generated = []
    for bench, cfg in config.items():
        if args.benchmark and bench != args.benchmark:
            continue
        for topology in cfg["topologies"]:
            chips = chips_of(topology)
            for kind in ("strong", "weak"):
                if kind == "strong":
                    n = cfg["size"]["strong"]
                elif cfg["size"].get("weak_scaling") == "sqrt":
                    # quadratic-cost workloads (n×n output): constant
                    # per-chip memory needs n ∝ sqrt(chips)
                    n = int(cfg["size"]["weak_per_chip"] * chips**0.5)
                else:
                    n = cfg["size"]["weak_per_chip"] * chips
                for suffix, params in parameters_for(bench, cfg, n):
                    name = f"{bench}{suffix}-{kind}-scale-{topology}"
                    multi_host = chips > 8
                    template = MULTI_HOST_TEMPLATE if multi_host else SINGLE_HOST_TEMPLATE
                    body = template.format(
                        name=name,
                        topology=topology,
                        chips=chips,
                        script=cfg["script"],
                        parameters=params,
                        output=f"{name}.out",
                        bench_rel=bench_rel,
                        tpu_name=args.tpu_name,
                        zone=args.zone,
                        project=args.project,
                        remote_dir=args.remote_dir,
                    )
                    path = os.path.join(args.out, name + ".sh")
                    with open(path, "w") as f:
                        f.write(body)
                    os.chmod(path, os.stat(path).st_mode | stat.S_IXUSR | stat.S_IXGRP)
                    generated.append(path)
    print(f"generated {len(generated)} jobscripts in {args.out}/")


if __name__ == "__main__":
    main()
